"""Replica groups and hedged dispatch for the serving layer.

Sharded serving (PR 7) removed the single-device capacity cap; this
module removes the single-*path* tail-latency cap (docs/SERVING.md
"Traffic shaping", docs/FAULT_MODEL.md "Hedged dispatch").  A
:class:`ReplicaSet` holds R copies of a service's pinned operand, each
committed to a **disjoint sub-mesh** of the session mesh (the host-group
decomposition HiCCL motivated for the hierarchical merge, reapplied to
placement), and dispatches every batch through three layers of defense:

**Rotation with per-replica breakers.**  Batches round-robin across
replicas; each replica carries its own
:class:`~raft_tpu.serve.resilience.CircuitBreaker`, so a persistently
failing replica *drops out of rotation* (and probes its way back in
through half-open) instead of tripping the whole service — the
service-level breaker only sees failures no replica could absorb.

**Hedged re-dispatch.**  A batch whose execution exceeds the hedge
threshold — fixed (``serve_hedge_ms``) or adaptive
(``serve_hedge_factor`` × the tracked per-bucket-rung p99, floored at
``serve_hedge_min_ms``) — is re-dispatched to a second replica.  First
successful result wins; the riders' futures resolve from the winner
exactly once (the worker thread is the only resolver, and the race
commits a single winner under a lock).

**Loser cancellation — the PR 4 watchdog commit handshake.**  Each arm
runs on a runner thread carrying the same
``raft_tpu_abandon_lock`` / ``raft_tpu_abandoned`` /
``raft_tpu_dispatch_committed`` attributes the comms watchdog uses
(:class:`~raft_tpu.comms.resilience.RetryPolicy`).  When the race
commits a winner, the loser is *abandoned under its lock*: a loser
still stalled host-side (an injected ``Delay``, a slow host stage)
checks the mark at the fault seam and bails **before dispatching its
program** — the same late-dispatch suppression that keeps an abandoned
comms attempt from racing its retry's collective.  A loser that already
committed its dispatch runs to completion and its result is discarded
(XLA work is not cancellable — the NCCL/watchdog stance), which is why
a hedge and a straggler can never both resolve the riders.

Metrics (labels ``service=`` plus ``replica=`` where noted):
``raft_tpu_serve_hedges_total`` (hedges fired),
``raft_tpu_serve_hedge_wins_total`` (hedge result used),
``raft_tpu_serve_hedge_cancelled_total`` (losers discarded/abandoned),
``raft_tpu_serve_replica_failovers_total`` (pre-hedge failure moved to
another replica), ``raft_tpu_serve_replica_errors_total{replica=}``,
``raft_tpu_serve_replica_exec_seconds{replica=}`` (per-replica
execution latency — the per-replica split of the adaptive hedge
threshold's signal; the traffic-shaping digest renders it),
``raft_tpu_serve_replica_state{replica=}`` (0=closed 1=open
2=half-open), ``raft_tpu_serve_replicas_healthy``.

Hedge decisions and winners are also recorded into the flight
recorder (``replica_dispatch`` / ``hedge`` / ``hedge_win`` /
``failover`` events, attached to every rider's trace via the worker's
batch scope — docs/OBSERVABILITY.md "Flight recorder & request
tracing").
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from raft_tpu.comms.faults import Fault, FaultInjector
from raft_tpu.core import flight
from raft_tpu.core import metrics as _metrics
from raft_tpu.core.error import (
    CALLER_BUG_ERRORS,
    ServiceUnavailableError,
    expects,
)
from raft_tpu.serve.resilience import BreakerState

__all__ = ["ReplicaSet", "split_mesh", "inject_replica",
           "ReplicaFaultInjector"]


def split_mesh(mesh, axis: str, replicas: int) -> List:
    """Cut a 1-D mesh into ``replicas`` disjoint contiguous sub-meshes
    along ``axis`` (``np.array_split`` sizes: as even as the device
    count allows).  Contiguous groups keep same-host devices together,
    so a replica's internal sharded merge stays on fast intra-host
    links — the host-group decomposition argument."""
    from jax.sharding import Mesh

    expects(axis in mesh.axis_names,
            "split_mesh: axis %r not in mesh axes %r", axis,
            tuple(mesh.axis_names))
    expects(len(mesh.axis_names) == 1,
            "split_mesh: replica groups need a 1-D mesh; got axes %r",
            tuple(mesh.axis_names))
    expects(replicas >= 2, "split_mesh: replicas=%d (need >= 2)",
            replicas)
    devs = list(mesh.devices.ravel())
    expects(len(devs) >= replicas,
            "split_mesh: %d devices cannot host %d disjoint replicas",
            len(devs), replicas)
    groups = np.array_split(np.asarray(devs, dtype=object), replicas)
    return [Mesh(np.asarray(g), (axis,)) for g in groups]


def _labeled(kind: str, name: str, help: str, service: str, **extra):
    label_names = ("service",) + tuple(sorted(extra))
    fam = getattr(_metrics.default_registry(), kind)(
        name, help=help, labels=label_names)
    return fam.labels(service=service, **extra)


class _LatencyTracker:
    """Execution-latency windows for the adaptive hedge threshold,
    tracked BOTH per bucket rung (the PR 8 aggregate) and per
    (replica, rung).  Thread-safe (losing arms record from their own
    threads); a rung with fewer than ``min_samples`` observations
    reports None — hedging stays off until the tracker has a real p99
    to multiply.

    The per-replica split exists because the aggregate alone is wrong
    under replica skew: one persistently slow replica inflates the
    shared rung p99, which *raises* the hedge threshold exactly when
    hedging should fire sooner.  :meth:`best_p99` — the minimum
    per-replica p99 at the rung — tracks what a *healthy* replica can
    do, so the threshold stays anchored to the latency a hedge could
    actually achieve."""

    def __init__(self, window: int = 64, min_samples: int = 5):
        self._lock = threading.Lock()
        self._window = int(window)
        self._min = int(min_samples)
        self._rungs: dict = {}
        self._replica_rungs: dict = {}   # (replica, rows) -> deque

    def observe(self, rows: int, seconds: float,
                replica: Optional[int] = None) -> None:
        with self._lock:
            dq = self._rungs.get(rows)
            if dq is None:
                dq = self._rungs[rows] = collections.deque(
                    maxlen=self._window)
            dq.append(float(seconds))
            if replica is not None:
                key = (int(replica), rows)
                rdq = self._replica_rungs.get(key)
                if rdq is None:
                    rdq = self._replica_rungs[key] = collections.deque(
                        maxlen=self._window)
                rdq.append(float(seconds))

    @staticmethod
    def _p99_of(dq) -> float:
        s = sorted(dq)
        return s[int(round(0.99 * (len(s) - 1)))]

    def p99(self, rows: int) -> Optional[float]:
        with self._lock:
            dq = self._rungs.get(rows)
            if dq is None or len(dq) < self._min:
                return None
            return self._p99_of(dq)

    def replica_p99(self, replica: int, rows: int) -> Optional[float]:
        with self._lock:
            dq = self._replica_rungs.get((int(replica), rows))
            if dq is None or len(dq) < self._min:
                return None
            return self._p99_of(dq)

    def best_p99(self, rows: int,
                 replicas: Optional[Sequence[int]] = None
                 ) -> Optional[float]:
        """The fastest replica's p99 at this rung (None until some
        replica has ``min_samples`` there) — the adaptive hedge
        threshold's anchor (class doc).

        ``replicas`` restricts the minimum to those indices — the
        caller passes the replicas currently IN ROTATION, because a
        dead replica's frozen fast window would otherwise anchor the
        threshold to a latency no survivor can meet (every batch would
        hedge, doubling device work, until the dead replica's stale
        window happened to be the slow one)."""
        with self._lock:
            allowed = None if replicas is None else set(replicas)
            best = None
            for (rep, r), dq in self._replica_rungs.items():
                if allowed is not None and rep not in allowed:
                    continue
                if r == rows and len(dq) >= self._min:
                    p = self._p99_of(dq)
                    if best is None or p < best:
                        best = p
            return best

    def samples(self, rows: int) -> int:
        with self._lock:
            dq = self._rungs.get(rows)
            return len(dq) if dq is not None else 0

    def per_replica(self) -> dict:
        """{replica: {rung: {"p99_ms", "samples"}}} — the
        traffic-shaping digest's per-replica latency table."""
        with self._lock:
            out: dict = {}
            for (rep, rows), dq in sorted(self._replica_rungs.items()):
                if not dq:
                    continue
                out.setdefault(rep, {})[rows] = {
                    "p99_ms": round(self._p99_of(dq) * 1e3, 3),
                    "samples": len(dq),
                }
            return out


class _Replica:
    """One replica: a sub-mesh, its execute path, and its breaker."""

    __slots__ = ("idx", "mesh", "execute", "breaker")

    def __init__(self, idx: int, mesh, execute: Callable, breaker):
        self.idx = idx
        self.mesh = mesh
        self.execute = execute
        self.breaker = breaker


class _Race:
    """First-success-wins commit point shared by a batch's arms (the
    exactly-once half of the hedge contract): the first arm to finish
    *successfully* commits itself as winner under the lock; everything
    later is a loser whose result is discarded."""

    __slots__ = ("lock", "event", "winner")

    def __init__(self):
        self.lock = threading.Lock()
        self.event = threading.Event()
        self.winner = None

    def finish(self, arm: "_Arm") -> bool:
        """Record one arm's completion; True when it committed as the
        winner."""
        with self.lock:
            won = arm.error is None and self.winner is None
            if won:
                self.winner = arm
        arm.done.set()
        self.event.set()
        return won


class _Arm:
    """One replica dispatch running on its own runner thread, carrying
    the watchdog commit-handshake attributes (module doc) so a stalled
    loser can be abandoned host-side."""

    __slots__ = ("replica", "out", "error", "seconds", "done", "thread",
                 "_race", "_clock", "_payload", "_on_finish")

    def __init__(self, replica: _Replica, payload, clock, race: _Race,
                 name: str, on_finish: Callable[["_Arm", bool], None]):
        self.replica = replica
        self.out = None
        self.error: Optional[BaseException] = None
        self.seconds: Optional[float] = None
        self.done = threading.Event()
        self._race = race
        self._clock = clock
        self._payload = payload
        self._on_finish = on_finish
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name="raft-tpu-hedge-%s-r%d" % (name, replica.idx))
        # the PR 4 commit handshake (comms/resilience.py): the fault
        # seam's Delay checks these under the lock, so abandon-vs-
        # commit resolves atomically for a stall straddling the hedge
        self.thread.raft_tpu_abandon_lock = threading.Lock()
        self.thread.start()

    def _run(self) -> None:
        t0 = self._clock()
        try:
            out = self.replica.execute(self._payload)
            leaves = [x for x in jax.tree_util.tree_leaves(out)
                      if hasattr(x, "shape")]
            jax.block_until_ready(leaves)
            self.out = out
            self.seconds = self._clock() - t0
        except BaseException as e:  # serve-exc-ok: relayed via the race
            # (run()/_settle_single re-raise losers' errors onto the
            # worker's batch-failure path; on_finish counts them into
            # raft_tpu_serve_replica_errors_total and the breaker)
            self.error = e
        won = self._race.finish(self)
        self._on_finish(self, won)

    def abandon(self) -> bool:
        """Cancel a losing arm host-side: mark its runner abandoned
        under the handshake lock.  A ``Delay``-stalled (or otherwise
        pre-dispatch) loser bails at the fault seam instead of
        dispatching its program late; a loser that already committed
        its dispatch runs to completion, result discarded.  Returns
        True when the loser had NOT yet committed (the cancellation
        actually suppressed a dispatch)."""
        with self.thread.raft_tpu_abandon_lock:
            committed = getattr(self.thread,
                                "raft_tpu_dispatch_committed", False)
            if not committed:
                self.thread.raft_tpu_abandoned = True
            return not committed


class ReplicaSet:
    """R replicas of one service operand over disjoint sub-meshes, with
    rotation, per-replica breakers, and hedged dispatch (module doc).

    Parameters
    ----------
    name:
        Service name (the ``service=`` metric label).
    members:
        ``[(mesh, execute), ...]`` — per replica, its sub-mesh and its
        ``execute(padded) -> pytree`` path (may launch asynchronously;
        the arm blocks until ready).
    hedge_s:
        Fixed hedge threshold in seconds; None = adaptive from the
        per-rung p99 tracker.
    hedge_factor / hedge_min_s:
        Adaptive threshold shape: ``max(factor * p99(rung), min_s)``.
    breakers:
        Optional per-replica breaker list (None entries = replica never
        drops out).
    clock:
        Monotonic-seconds source (the shared injectable-clock seam).
    """

    def __init__(self, name: str, members: List[Tuple],
                 *, hedge_s: Optional[float],
                 hedge_factor: float, hedge_min_s: float,
                 breakers: Optional[List] = None,
                 window: int = 64, min_samples: int = 5,
                 clock: Callable[[], float] = time.monotonic):
        expects(len(members) >= 2,
                "ReplicaSet: %d members (need >= 2 — one replica is "
                "just a service)", len(members))
        self.name = name
        self.replicas = [
            _Replica(i, mesh, fn,
                     breakers[i] if breakers is not None else None)
            for i, (mesh, fn) in enumerate(members)]
        self.hedge_s = None if hedge_s is None else float(hedge_s)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_s = float(hedge_min_s)
        self.tracker = _LatencyTracker(window, min_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._rr = 0
        self._publish_states()

    # ------------------------------------------------------------------ #
    # rotation
    # ------------------------------------------------------------------ #
    def _pick(self, exclude: Tuple[int, ...] = ()) -> Optional[_Replica]:
        """Next replica in rotation whose breaker admits (a half-open
        breaker's admission IS its probe), or None when every replica
        is excluded or tripped."""
        with self._lock:
            n = len(self.replicas)
            for off in range(n):
                r = self.replicas[(self._rr + off) % n]
                if r.idx in exclude:
                    continue
                if r.breaker is None or r.breaker.allow():
                    self._rr = (self._rr + off + 1) % n
                    return r
            return None

    def _publish_states(self) -> None:
        healthy = 0
        for r in self.replicas:
            state = (BreakerState.CLOSED if r.breaker is None
                     else r.breaker.state)
            if state is not BreakerState.OPEN:
                healthy += 1
            _labeled("gauge", "raft_tpu_serve_replica_state",
                     "per-replica breaker state (0=closed 1=open "
                     "2=half-open)", self.name,
                     replica=r.idx).set(state.value)
        _labeled("gauge", "raft_tpu_serve_replicas_healthy",
                 "replicas currently in rotation (breaker not open)",
                 self.name).set(healthy)

    def device_ids(self) -> set:
        """All device ids the replica set spans (session health_check
        validates them against the current mesh)."""
        return {int(d.id) for r in self.replicas
                for d in r.mesh.devices.ravel()}

    def describe(self) -> dict:
        per_replica_lat = self.tracker.per_replica()
        return {
            "replicas": [
                {"idx": r.idx,
                 "devices": [int(d.id) for d in r.mesh.devices.ravel()],
                 "state": ((BreakerState.CLOSED if r.breaker is None
                            else r.breaker.state).name.lower()),
                 # per-(replica, rung) latency window — the signal the
                 # adaptive hedge threshold anchors on (hedge_after)
                 "latency": per_replica_lat.get(r.idx, {})}
                for r in self.replicas],
            "hedge_ms": (None if self.hedge_s is None
                         else self.hedge_s * 1e3),
            "hedge_factor": self.hedge_factor,
            "hedge_min_ms": self.hedge_min_s * 1e3,
        }

    # ------------------------------------------------------------------ #
    # warmup
    # ------------------------------------------------------------------ #
    def warm(self, payload) -> None:
        """Run ``payload`` through EVERY replica's execute path (each
        sub-mesh compiles its own executables — warming one replica
        proves nothing about the others)."""
        for r in self.replicas:
            out = r.execute(payload)
            jax.block_until_ready(
                [x for x in jax.tree_util.tree_leaves(out)
                 if hasattr(x, "shape")])

    # ------------------------------------------------------------------ #
    # dispatch
    # ------------------------------------------------------------------ #
    def hedge_after(self, rows: int) -> Optional[float]:
        """Seconds to wait on the primary before hedging a ``rows``-row
        batch (None = never hedge: no fixed threshold and the tracker
        has too few samples at this rung).

        Adaptive mode anchors on the FASTEST *in-rotation* replica's
        per-(replica, rung) p99 rather than the shared rung aggregate
        — one slow replica must not raise the threshold that decides
        when to hedge *away from it* (the PR 8 residual), and a dead
        replica's frozen fast window must not anchor a threshold no
        survivor can meet.  The aggregate is the cold-start fallback
        until any single replica has enough samples at the rung."""
        if self.hedge_s is not None:
            return self.hedge_s
        in_rotation = [r.idx for r in self.replicas
                       if r.breaker is None
                       or r.breaker.state is not BreakerState.OPEN]
        p = self.tracker.best_p99(rows, replicas=in_rotation)
        if p is None:
            p = self.tracker.p99(rows)
        if p is None:
            return None
        return max(self.hedge_factor * p, self.hedge_min_s)

    def _on_arm_finish(self, arm: _Arm, won: bool) -> None:
        """Bookkeeping for EVERY arm — winners and losers alike — run
        on the arm's own thread: latency samples feed the tracker, and
        the replica's breaker sees its replica's true outcome even when
        the race already resolved the riders elsewhere."""
        r = arm.replica
        if arm.error is None:
            if arm.seconds is not None:
                self.tracker.observe(int(arm._payload.shape[0]),
                                     arm.seconds, replica=r.idx)
                _labeled("timer", "raft_tpu_serve_replica_exec_seconds",
                         "batch execution latency per replica (the "
                         "per-replica split of the hedge threshold's "
                         "latency signal)", self.name,
                         replica=r.idx).observe(arm.seconds)
            if r.breaker is not None:
                r.breaker.record_success()
        else:
            _labeled("counter", "raft_tpu_serve_replica_errors_total",
                     "batch executions that failed, per replica",
                     self.name, replica=r.idx).inc()
            if (r.breaker is not None
                    and not isinstance(arm.error, CALLER_BUG_ERRORS)):
                r.breaker.record_failure(arm.error)
        self._publish_states()

    def _shed_exhausted(self) -> None:
        raise ServiceUnavailableError(
            "%s: every replica's breaker is open — no replica can "
            "carry this batch; back off and retry" % self.name,
            self.name, "replicas_exhausted", 0.0)

    def run(self, padded):
        """Dispatch one padded batch: rotation-picked primary, hedge on
        straggle, failover-once on failure (class doc).  Returns the
        winning result pytree (already device-ready); raises when no
        replica could serve — the worker relays that to the riders
        through the normal batch-failure path."""
        rows = int(padded.shape[0])
        primary = self._pick()
        if primary is None:
            self._shed_exhausted()
        # attaches to every rider of the current batch (the worker's
        # flight.batch_scope) — the trace's "which replica carried me"
        flight.record_scoped("replica_dispatch", service=self.name,
                             replica=primary.idx, rows=rows)
        threshold = self.hedge_after(rows)
        if threshold is None:
            # hedging cannot fire (adaptive threshold still cold): no
            # point paying a runner thread per batch — execute inline
            # on the worker thread, keeping the failover path (and
            # feeding the tracker the samples that turn hedging on)
            return self._run_inline(primary, padded, rows)
        race = _Race()
        arm = _Arm(primary, padded, self._clock, race, self.name,
                   self._on_arm_finish)
        if arm.done.wait(threshold):
            return self._settle_single(arm, padded)
        hedge_rep = self._pick(exclude=(primary.idx,))
        if hedge_rep is None:
            # no spare replica in rotation: nothing to hedge to — wait
            # the straggler out (the pre-replica behavior)
            arm.done.wait()
            return self._settle_single(arm, padded)
        _labeled("counter", "raft_tpu_serve_hedges_total",
                 "hedged re-dispatches fired on straggling batches",
                 self.name).inc()
        flight.record_scoped("hedge", service=self.name,
                             primary=primary.idx, hedge=hedge_rep.idx,
                             threshold_s=round(threshold, 6))
        arm2 = _Arm(hedge_rep, padded, self._clock, race, self.name,
                    self._on_arm_finish)
        arms = (arm, arm2)
        while True:
            race.event.wait()
            race.event.clear()
            # winner and all-done must be read under ONE lock hold:
            # finish() commits the winner before setting done, so a
            # stale winner=None read paired with a later all-done
            # check would discard a valid result and raise instead
            with race.lock:
                winner = race.winner
                all_done = all(a.done.is_set() for a in arms)
            if winner is not None:
                break
            if all_done:
                # both arms failed: relay the hedge's error (the later
                # attempt — the primary's error already burned its
                # chance); per-replica breakers were fed by on_finish
                raise arm2.error if arm2.error is not None else arm.error
        loser = arm2 if winner is arm else arm
        # loser cancellation (module doc): abandon under the commit
        # handshake — a pre-dispatch loser never launches its program
        loser.abandon()
        _labeled("counter", "raft_tpu_serve_hedge_cancelled_total",
                 "hedge losers abandoned or discarded (exactly one per "
                 "fired hedge)", self.name).inc()
        if winner is arm2:
            _labeled("counter", "raft_tpu_serve_hedge_wins_total",
                     "hedged re-dispatches whose result beat the "
                     "straggling primary", self.name).inc()
        flight.record_scoped("hedge_win", service=self.name,
                             winner=winner.replica.idx,
                             loser=loser.replica.idx,
                             hedge_won=winner is arm2)
        return winner.out

    def _execute_blocking(self, replica: _Replica, padded, rows: int):
        """One inline replica execution on the calling thread, with the
        same bookkeeping an arm's on_finish does; raises on failure."""
        t0 = self._clock()
        try:
            out = replica.execute(padded)
            jax.block_until_ready(
                [x for x in jax.tree_util.tree_leaves(out)
                 if hasattr(x, "shape")])
        except BaseException as e:
            _labeled("counter", "raft_tpu_serve_replica_errors_total",
                     "batch executions that failed, per replica",
                     self.name, replica=replica.idx).inc()
            if (replica.breaker is not None
                    and not isinstance(e, CALLER_BUG_ERRORS)):
                replica.breaker.record_failure(e)
            self._publish_states()
            raise
        seconds = self._clock() - t0
        self.tracker.observe(rows, seconds, replica=replica.idx)
        _labeled("timer", "raft_tpu_serve_replica_exec_seconds",
                 "batch execution latency per replica (the "
                 "per-replica split of the hedge threshold's "
                 "latency signal)", self.name,
                 replica=replica.idx).observe(seconds)
        if replica.breaker is not None:
            replica.breaker.record_success()
        self._publish_states()
        return out

    def _failover(self, failed_idx: int, padded, rows: int, err):
        """Move a failed batch to the next healthy replica ONCE (the
        tripped-replica-drops-out contract: one bad replica must not
        fail the batch while healthy replicas idle); re-raises ``err``
        when no other replica is in rotation."""
        alt = self._pick(exclude=(failed_idx,))
        if alt is None:
            raise err
        _labeled("counter", "raft_tpu_serve_replica_failovers_total",
                 "batches moved to another replica after a primary "
                 "failure", self.name).inc()
        flight.record_scoped("failover", service=self.name,
                             failed=failed_idx, to=alt.idx,
                             error=type(err).__name__)
        return self._execute_blocking(alt, padded, rows)

    def _run_inline(self, primary: _Replica, padded, rows: int):
        try:
            return self._execute_blocking(primary, padded, rows)
        except BaseException as e:
            if isinstance(e, CALLER_BUG_ERRORS) or not isinstance(
                    e, Exception):
                raise
            return self._failover(primary.idx, padded, rows, e)

    def _settle_single(self, arm: _Arm, padded):
        """Resolve an un-hedged arm: return its result, or fail over
        once (:meth:`_failover`)."""
        if arm.error is None:
            return arm.out
        err = arm.error
        if isinstance(err, CALLER_BUG_ERRORS) or not isinstance(
                err, Exception):
            raise err  # caller bugs and worker-killers take their path
        return self._failover(arm.replica.idx, padded,
                              int(padded.shape[0]), err)


# ---------------------------------------------------------------------- #
# per-replica fault injection (the chaos seam for hedging tests)
# ---------------------------------------------------------------------- #
class ReplicaFaultInjector(FaultInjector):
    """Patch ONE replica's execute seam with the comms fault vocabulary
    (:mod:`raft_tpu.comms.faults`) — the seam the hedged-dispatch chaos
    scenario needs: a ``Delay`` on one replica makes it a straggler
    (hedge fires, the delayed loser is abandoned at this very seam via
    the commit handshake), a persistent ``FailNth`` makes it a dead
    replica (its breaker trips it out of rotation).  Verb:
    ``"serve.<service>.r<idx>"``; ``Abort`` is unsupported (no
    communicator to latch)."""

    def __init__(self, service, idx: int, faults_: List[Fault]):
        rs = getattr(service, "_replica_set", None)
        expects(rs is not None,
                "inject_replica: service %r is not replicated",
                getattr(service, "name", service))
        expects(0 <= idx < len(rs.replicas),
                "inject_replica: replica %d out of range (%d replicas)",
                idx, len(rs.replicas))
        self._replica = rs.replicas[idx]
        super().__init__(self._replica, faults_)
        self.verb = "serve.%s.r%d" % (rs.name, idx)

    def activate(self) -> None:
        assert self._orig_execute is None, "injector already active"
        rep = self._replica
        self._orig_execute = rep.execute
        orig = self._orig_execute
        verb = self.verb

        def patched(padded):
            rows = int(getattr(padded, "shape", (0,))[0])
            self._fire(rep, verb, (verb, rows))
            return orig(padded)

        rep.execute = patched

    def deactivate(self) -> None:
        if self._orig_execute is not None:
            self._replica.execute = self._orig_execute
            self._orig_execute = None


@contextlib.contextmanager
def inject_replica(service, idx: int,
                   *faults_: Fault) -> Iterator[ReplicaFaultInjector]:
    """Scoped per-replica fault injection: patch replica ``idx``'s
    execute seam for the duration of the block, restore after (even on
    error)::

        with inject_replica(svc, 0, faults.Delay(0.5)):
            ...   # replica 0 straggles; hedges fire to replica 1
    """
    injector = ReplicaFaultInjector(service, idx, list(faults_))
    injector.activate()
    try:
        yield injector
    finally:
        injector.deactivate()
