"""Service facades: warmup / submit / drain / close over hot primitives.

A service pins the heavy, shape-stable half of a query workload at
construction (the kNN index partition, the pairwise reference matrix,
k, the metric) and serves the light, shape-varying half (query rows)
through the micro-batching engine:

- :class:`KNNService`  — ``submit((n_i, d) queries) -> (dists, ids)``
  over :func:`raft_tpu.spatial.brute_force_knn`;
- :class:`PairwiseService` — ``submit((n_i, d) x) -> (n_i, n_y)`` over
  :func:`raft_tpu.distance.pairwise_distance`.

Both call their device function only at bucket shapes, so the heavy
programs' executable-cache cardinality is exactly the rung count,
:meth:`Service.warmup` precompiles every rung through the existing
:func:`~raft_tpu.core.profiler.profiled_jit` lowering path before
traffic arrives, and ``compile_cache_stats()`` proves (the serving SLO
statement) that steady state performs **zero** compiles.  Where the
jit boundary sits differs deliberately:

- kNN calls :func:`brute_force_knn` *eagerly* per batch; its scan
  (``tiled_knn``, already ``profiled_jit``) is the cached program.  An
  outer jit would fuse across the eager call's inner-jit boundaries
  and change low-bit float results — measured 1e-6 drift — breaking
  the bit-identical-to-unbatched contract this layer promises.
- pairwise has no inner jit (it is eager jnp ops), so the service
  wraps the whole call in ``profiled_jit`` (``serve_pairwise``) to get
  one AOT-compiled program per bucket; identity holds vs the same
  jitted program, low bits may differ vs the eager call.

(Glue ops around the cached program — concatenate/pad at arrival-
pattern-dependent shapes — compile tiny copy programs in JAX's own
cache; the bucket ladder bounds the *expensive* programs.)

Optional per-service query-vector cache: an LRU
:class:`~raft_tpu.cache.VecCache` keyed by caller ids
(``query_cache_size > 0``) lets repeat queries be submitted *by key*
(:meth:`Service.submit_keys`) without re-shipping the vector; hit/miss
counters land in the registry.

Results are bit-identical to the unbatched primitive: pad rows are
zeros, every fronted primitive is row-independent, and the per-request
slices are exact.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.cache import VecCache
from raft_tpu.core.error import (
    LogicError,
    ServiceOverloadError,
    ServiceUnavailableError,
    expects,
)
from raft_tpu.core.profiler import profiled_jit
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.serve.batcher import MicroBatcher, ServeFuture
from raft_tpu.serve.bucketing import BucketPolicy, resolve_rungs
from raft_tpu.serve.resilience import BreakerState, CircuitBreaker
from raft_tpu.serve.scheduler import ServeWorker, _counter, _gauge
from raft_tpu.spatial.knn import brute_force_knn

__all__ = ["Service", "KNNService", "PairwiseService"]

_service_seq = itertools.count()


def _knob_float(name: str) -> float:
    raw = config.get(name)
    try:
        return float(raw)
    except (TypeError, ValueError):
        raise ValueError("raft_tpu.config: %s=%r is not a number"
                         % (name, raw)) from None


def _knob_int(name: str) -> int:
    raw = config.get(name)
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise ValueError("raft_tpu.config: %s=%r is not an integer"
                         % (name, raw)) from None


# -- device functions -------------------------------------------------- #
# module-level + profiled_jit: one executable cache per (fn, shapes,
# statics) across ALL services, with per-bucket hit/miss/compile-seconds
# visible through compile_cache_stats() under this name.  (The kNN
# service deliberately has no such wrapper — see the module doc — its
# cached program is tiled_knn's existing profiled_jit.)
def _pairwise_impl(y, queries, metric):
    return pairwise_distance(queries, y, metric)


_pairwise_device = profiled_jit(
    name="serve_pairwise", static_argnames=("metric",))(_pairwise_impl)
# the donating twin (zero-copy serve path, docs/ZERO_COPY.md): the
# padded batch buffer is CONSUMED by the call and recycled for the
# output.  A separate wrapper (and stats name), not a flag — a donating
# and a non-donating executable must never share a cache slot
_pairwise_device_donated = profiled_jit(
    name="serve_pairwise_donated", static_argnames=("metric",),
    donate_argnames=("queries",))(_pairwise_impl)


class Service:
    """Micro-batching façade over one device function.

    Parameters
    ----------
    execute:
        ``execute(padded_queries) -> pytree`` with batch-rows-leading
        leaves (subclasses bind the pinned operands).
    dim / dtype:
        Query row shape contract; enforced at ``submit``.
    max_batch_rows:
        Top bucket rung = device-call row cap = per-request row cap.
    bucket_rungs / max_wait_ms / queue_cap:
        Shape ladder, micro-batch window, admission cap; each defaults
        to its ``serve_*`` knob in :mod:`raft_tpu.config`.
    retry_policy:
        Optional per-batch :class:`~raft_tpu.comms.resilience.RetryPolicy`
        (watchdog deadline + retries around the device call).
    donate:
        Donate the padded batch buffer to the bucketed executable
        (docs/ZERO_COPY.md): the buffer is serve-internal, so
        recycling it costs nothing and saves one output allocation per
        batch.  Default: on whenever no ``retry_policy`` is set (a
        retry would replay a consumed buffer); pass ``False`` to opt
        out.
    breaker:
        The service circuit breaker
        (:class:`~raft_tpu.serve.resilience.CircuitBreaker`;
        docs/FAULT_MODEL.md "Serving failure model").  Default (None):
        construct one from the ``serve_breaker_*`` config knobs —
        every service is breaker-protected out of the box.  Pass a
        configured instance to tune it, or ``False`` to opt out
        entirely (PR 3's relay-every-failure behavior).
    query_cache_size:
        > 0 enables the :class:`VecCache` query-vector cache
        (:meth:`cache_put` / :meth:`submit_keys`).
    maintenance / maintenance_interval_s:
        Optional background-work callback run on the worker thread
        between batches (see :class:`ServeWorker`) — the ANN service's
        compaction seam.
    start:
        Spawn the worker thread now (False = threadless: tests drive
        :attr:`worker` ``.run_once()`` under an injected ``clock``).
    """

    def __init__(self, name: str, execute: Callable, dim: int,
                 dtype=jnp.float32, *,
                 max_batch_rows: int = 1024,
                 bucket_rungs=None,
                 max_wait_ms: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 retry_policy=None,
                 donate: Optional[bool] = None,
                 breaker=None,
                 query_cache_size: int = 0,
                 maintenance: Optional[Callable[[], None]] = None,
                 maintenance_interval_s: float = 0.05,
                 start: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        expects(dim >= 1, "Service: dim=%d", dim)
        self.name = name
        self.dim = int(dim)
        self.dtype = jnp.dtype(dtype)
        self._execute = execute
        self._clock = clock
        # donation INTENT only (default on): ServeWorker owns the
        # retry-gating rule; the resolved value is read back from the
        # worker below, and subclasses use it to pick their device-fn
        # variant
        donate_intent = True if donate is None else bool(donate)
        if bucket_rungs is None:
            bucket_rungs = config.get("serve_bucket_rungs")
        if max_wait_ms is None:
            max_wait_ms = _knob_float("serve_max_wait_ms")
        if queue_cap is None:
            queue_cap = _knob_int("serve_queue_cap")
        self.policy = BucketPolicy(
            resolve_rungs(bucket_rungs, int(max_batch_rows)))
        self.batcher = MicroBatcher(
            max_batch_rows=self.policy.max_rows,
            max_wait_s=float(max_wait_ms) / 1e3,
            queue_cap=int(queue_cap), clock=clock, name=name)
        if breaker is None:
            threshold = _knob_int("serve_breaker_threshold")
            window_failures = _knob_int("serve_breaker_window_failures")
            if threshold == 0 and window_failures == 0:
                # both trip conditions knobbed off == breaker off (the
                # env-level opt-out; breaker=False is the code-level
                # one) — a breaker that can never open is just overhead
                breaker = None
            else:
                breaker = CircuitBreaker(
                    name,
                    failure_threshold=threshold,
                    window=_knob_int("serve_breaker_window"),
                    window_failures=window_failures,
                    cooldown_s=_knob_float("serve_breaker_cooldown_ms")
                    / 1e3,
                    clock=clock)
        elif breaker is False:
            breaker = None
        self.breaker = breaker
        self.worker = ServeWorker(name, self.batcher, self.policy,
                                  execute, retry_policy=retry_policy,
                                  donate=donate_intent,
                                  maintenance=maintenance,
                                  maintenance_interval_s=(
                                      maintenance_interval_s),
                                  breaker=breaker,
                                  clock=clock)
        self.donate = self.worker.donate
        self._warmed: Tuple[int, ...] = ()
        self._closed = False
        self._cache_lock = threading.Lock()
        self._cache: Optional[VecCache] = None
        self._cache_state = None
        if query_cache_size > 0:
            self._cache = VecCache(self.dim, int(query_cache_size),
                                   dtype=self.dtype)
            self._cache_state = self._cache.init()
        if start:
            self.worker.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def warmup(self) -> "Service":
        """AOT-precompile every bucket rung through the device function
        (zeros payloads; results discarded after ``block_until_ready``).
        After warmup, steady-state traffic at any admissible shape runs
        entirely on cache hits — assert it via ``compile_cache_stats()``.
        """
        for rung in self.policy.rungs:
            out = self._execute(jnp.zeros((rung, self.dim), self.dtype))
            jax.block_until_ready(out)
        self._warmed = self.policy.rungs
        return self

    @property
    def warmed_rungs(self) -> Tuple[int, ...]:
        return self._warmed

    def is_open(self) -> bool:
        return not self._closed

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission, serve out the queue; True when empty."""
        return self.worker.drain(timeout=timeout)

    # -- recovery seams (raft_tpu/serve/resilience.py) ----------------- #
    def pause(self) -> None:
        """Suspend the service for recovery: new submits shed with
        :class:`~raft_tpu.core.error.ServiceUnavailableError`
        (``reason="recovering"``), batch formation stops, queued
        requests wait.  Reversible (:meth:`resume`) — unlike drain."""
        self.batcher.pause()

    def resume(self) -> None:
        """Re-admit after :meth:`pause`: batch formation restarts (the
        queued backlog first) and the breaker — whose history described
        the pre-recovery world — is reset closed."""
        self.batcher.resume()
        if self.breaker is not None:
            self.breaker.reset()

    def post_recover(self) -> None:
        """Hook run by :class:`~raft_tpu.serve.resilience.RecoveryManager`
        after a communicator/mesh rebuild, before ``warmup()``.  The
        base services pin only immutable operands — nothing to redo;
        :class:`~raft_tpu.serve.ann_service.ANNService` re-publishes
        its ``(index, delta)`` snapshot here."""

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Drain (by default) and stop the worker.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.worker.close(drain=drain, timeout=timeout)

    def __enter__(self) -> "Service":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # submission
    # ------------------------------------------------------------------ #
    def _check_payload(self, queries) -> jnp.ndarray:
        q = jnp.asarray(queries)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "%s.submit: expected (rows, %d) queries, got %r",
                self.name, self.dim, tuple(q.shape))
        return q.astype(self.dtype)

    def submit(self, queries, timeout: Optional[float] = None
               ) -> ServeFuture:
        """Enqueue a query block; returns a future resolving to this
        service's result slice for exactly those rows.

        ``timeout`` is the request's end-to-end deadline in seconds: if
        it expires while the request is still queued, the future fails
        with :class:`~raft_tpu.core.error.CommTimeoutError` instead of
        occupying a batch (deadline-aware shedding).

        Unavailability sheds FAST with
        :class:`~raft_tpu.core.error.ServiceUnavailableError` before
        anything is queued: a dead worker thread (the queue would only
        absorb requests nobody serves — restart/recover first), an open
        circuit breaker (``retry_after_s`` carries the cooldown), or a
        recovery in progress.
        """
        expects(not self._closed, "%s.submit: service is closed",
                self.name)
        # payload validation FIRST: a malformed request is the caller's
        # bug and must not consume a half-open probe slot
        q = self._check_payload(queries)
        self._check_available()
        deadline_t = None if timeout is None else self._clock() + timeout
        try:
            fut = self.batcher.submit(q, int(q.shape[0]), deadline_t)
        except ServiceOverloadError:
            _counter("raft_tpu_serve_rejected_total",
                     "requests shed by admission control",
                     self.name).inc()
            raise
        _counter("raft_tpu_serve_submitted_total",
                 "admitted requests", self.name).inc()
        _gauge("raft_tpu_serve_queue_depth", "requests queued",
               self.name).set(self.batcher.depth())
        return fut

    def _shed_unavailable(self, message: str, reason: str,
                          retry_after_s: float = 0.0) -> None:
        _counter("raft_tpu_serve_unavailable_total",
                 "requests shed because the service is broken or "
                 "healing (breaker open / dead worker / recovering)",
                 self.name).inc()
        raise ServiceUnavailableError(message, self.name, reason,
                                      retry_after_s)

    def _check_available(self) -> None:
        """The fail-fast half of admission (docs/FAULT_MODEL.md): a
        request must never be queued into a service that cannot
        possibly serve it."""
        w = self.worker
        if w.dead():
            self._shed_unavailable(
                "%s.submit: worker thread has died — restart() or "
                "recover before resubmitting" % self.name,
                "worker_dead")
        if self.batcher.paused():
            self._shed_unavailable(
                "%s.submit: recovery in progress" % self.name,
                "recovering")
        if self.breaker is not None and not self.breaker.allow():
            half_open = self.breaker.state is BreakerState.HALF_OPEN
            self._shed_unavailable(
                "%s.submit: circuit breaker is %s — back off and "
                "retry" % (self.name,
                           "half-open (probe budget spent)"
                           if half_open else "open"),
                "breaker_half_open" if half_open else "breaker_open",
                self.breaker.retry_after())

    def submit_many(self, blocks: Sequence,
                    timeout: Optional[float] = None) -> List[ServeFuture]:
        """Submit several query blocks; one future each, same deadline."""
        return [self.submit(b, timeout=timeout) for b in blocks]

    # ------------------------------------------------------------------ #
    # query-vector cache (the dormant cache/VecCache, wired in)
    # ------------------------------------------------------------------ #
    def _require_cache(self) -> VecCache:
        expects(self._cache is not None,
                "%s: no query cache (construct with query_cache_size>0)",
                self.name)
        return self._cache

    def cache_put(self, keys, vectors) -> None:
        """Store query vectors under caller ids for later
        :meth:`submit_keys` (functional :class:`VecCache` state swapped
        under a lock — concurrent submitters stay consistent)."""
        cache = self._require_cache()
        k = jnp.asarray(keys, jnp.int32).ravel()
        v = self._check_payload(vectors)
        expects(k.shape[0] == v.shape[0],
                "%s.cache_put: %d keys for %d vectors", self.name,
                k.shape[0], v.shape[0])
        expects(k.shape[0] == 0 or bool((k >= 0).all()),
                "%s.cache_put: negative keys (the cache reserves -1 "
                "for empty ways)", self.name)
        with self._cache_lock:
            self._cache_state = cache.store_vecs(self._cache_state, k, v)

    def cache_lookup(self, keys) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Fetch cached vectors for ``keys``; returns ``(vectors,
        found)`` and feeds the hit/miss counters."""
        cache = self._require_cache()
        k = jnp.asarray(keys, jnp.int32).ravel()
        with self._cache_lock:
            vecs, found, self._cache_state = cache.get_vecs(
                self._cache_state, k)
        hits = int(found.sum())
        if hits:
            _counter("raft_tpu_serve_query_cache_hits_total",
                     "query-vector cache hits", self.name).inc(hits)
        if hits < k.shape[0]:
            _counter("raft_tpu_serve_query_cache_misses_total",
                     "query-vector cache misses", self.name).inc(
                         k.shape[0] - hits)
        return vecs, found

    def submit_keys(self, keys, timeout: Optional[float] = None
                    ) -> ServeFuture:
        """Submit queries *by cached id* — the repeat-query fast path
        (e.g. a stored user embedding queried on every page view).
        Every key must be cached; missing keys raise
        :class:`LogicError` naming them."""
        k = jnp.asarray(keys, jnp.int32).ravel()
        vecs, found = self.cache_lookup(k)
        if not bool(found.all()):
            missing = [int(x) for x in k[~found]][:16]
            raise LogicError(
                "%s.submit_keys: keys not in the query cache: %r%s"
                % (self.name, missing,
                   "..." if (~found).sum() > 16 else ""))
        return self.submit(vecs, timeout=timeout)

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        """Small live-state dict (health_check embeds it)."""
        out = {
            "open": self.is_open(),
            "worker_started": self.worker.started(),
            "worker_alive": self.worker.is_alive(),
            "queue_depth": self.batcher.depth(),
            "rows_queued": self.batcher.rows_queued(),
            "rungs": list(self.policy.rungs),
            "warmed": bool(self._warmed),
            "paused": self.batcher.paused(),
            # a silently failing compactor/maintenance callback must be
            # visible here, not only as a bare counter
            "last_maintenance_error": self.worker.last_maintenance_error,
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.describe()
        return out


class KNNService(Service):
    """Micro-batched :func:`brute_force_knn` over one pinned index
    partition.

    ``submit((n_i, d))`` futures resolve to ``(distances, indices)`` of
    shape ``(n_i, k)`` — bit-identical to the unbatched
    ``brute_force_knn(index, queries, k)`` call (pad rows are zeros and
    every row's result depends only on its own query row).
    """

    def __init__(self, index, k: int,
                 metric: DistanceType = DistanceType.L2Expanded,
                 tile_n: int = 8192, precision: str = "highest",
                 name: Optional[str] = None, **opts):
        index = jnp.asarray(index)
        expects(index.ndim == 2, "KNNService: (n, d) index required")
        expects(1 <= k <= index.shape[0],
                "KNNService: k=%d out of range for n_index=%d",
                k, index.shape[0])
        self.index = index
        self.k = int(k)
        self.metric = metric

        def execute(padded):
            # eager on purpose: bit-identical to the unbatched call
            # (module doc); the scan inside is the per-bucket cached
            # program.  donate_queries routes the padded buffer into
            # the scan's donating executable twin (identical program,
            # recycled input — docs/ZERO_COPY.md); self.donate is set
            # by Service.__init__ before any batch can run
            return brute_force_knn(self.index, padded, self.k,
                                   metric=self.metric, tile_n=tile_n,
                                   precision=precision,
                                   donate_queries=self.donate)

        super().__init__(
            name or "knn%d" % next(_service_seq), execute,
            dim=index.shape[1], dtype=index.dtype, **opts)


class PairwiseService(Service):
    """Micro-batched :func:`pairwise_distance` against one pinned
    reference matrix; futures resolve to the ``(n_i, n_y)`` block."""

    def __init__(self, y,
                 metric: DistanceType = DistanceType.L2Expanded,
                 name: Optional[str] = None, **opts):
        y = jnp.asarray(y)
        expects(y.ndim == 2, "PairwiseService: (n, d) reference required")
        self.y = y
        self.metric = metric

        def execute(padded):
            fn = (_pairwise_device_donated if self.donate
                  else _pairwise_device)
            return fn(self.y, padded, metric=self.metric)

        super().__init__(
            name or "pairwise%d" % next(_service_seq), execute,
            dim=y.shape[1], dtype=y.dtype, **opts)
