"""Service facades: warmup / submit / drain / close over hot primitives.

A service pins the heavy, shape-stable half of a query workload at
construction (the kNN index partition, the pairwise reference matrix,
k, the metric) and serves the light, shape-varying half (query rows)
through the micro-batching engine:

- :class:`KNNService`  — ``submit((n_i, d) queries) -> (dists, ids)``
  over :func:`raft_tpu.spatial.brute_force_knn` — or, with ``axis=``,
  over the mesh-sharded SPMD search
  :func:`raft_tpu.spatial.mnmg_knn` (docs/SERVING.md "Sharded
  serving": the index is row-sharded over a mesh axis ONCE at
  construction, every padded batch runs one pjit'd per-shard search +
  on-device top-k merge, and QPS scales with the mesh instead of one
  device's FLOPs);
- :class:`PairwiseService` — ``submit((n_i, d) x) -> (n_i, n_y)`` over
  :func:`raft_tpu.distance.pairwise_distance`.

Both call their device function only at bucket shapes, so the heavy
programs' executable-cache cardinality is exactly the rung count,
:meth:`Service.warmup` precompiles every rung through the existing
:func:`~raft_tpu.core.profiler.profiled_jit` lowering path before
traffic arrives, and ``compile_cache_stats()`` proves (the serving SLO
statement) that steady state performs **zero** compiles.  Where the
jit boundary sits differs deliberately:

- kNN calls :func:`brute_force_knn` *eagerly* per batch; its scan
  (``tiled_knn``, already ``profiled_jit``) is the cached program.  An
  outer jit would fuse across the eager call's inner-jit boundaries
  and change low-bit float results — measured 1e-6 drift — breaking
  the bit-identical-to-unbatched contract this layer promises.
- pairwise has no inner jit (it is eager jnp ops), so the service
  wraps the whole call in ``profiled_jit`` (``serve_pairwise``) to get
  one AOT-compiled program per bucket; identity holds vs the same
  jitted program, low bits may differ vs the eager call.

(Glue ops around the cached program — concatenate/pad at arrival-
pattern-dependent shapes — compile tiny copy programs in JAX's own
cache; the bucket ladder bounds the *expensive* programs.)

Optional per-service query-vector cache: an LRU
:class:`~raft_tpu.cache.VecCache` keyed by caller ids
(``query_cache_size > 0``) lets repeat queries be submitted *by key*
(:meth:`Service.submit_keys`) without re-shipping the vector; hit/miss
counters land in the registry.

Results are bit-identical to the unbatched primitive: pad rows are
zeros, every fronted primitive is row-independent, and the per-request
slices are exact.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.cache import VecCache
from raft_tpu.core import flight
from raft_tpu.core.error import (
    LogicError,
    ServiceOverloadError,
    ServiceUnavailableError,
    expects,
)
from raft_tpu.core.profiler import profiled_jit
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import pairwise_distance
from typing import NamedTuple

from raft_tpu.serve.batcher import MicroBatcher, ServeFuture
from raft_tpu.serve.bucketing import BucketPolicy, resolve_rungs
from raft_tpu.serve.resilience import BreakerState, CircuitBreaker
from raft_tpu.serve.scheduler import (ServeWorker, _counter, _gauge,
                                      _tenant_counter)
from raft_tpu.spatial.knn import brute_force_knn

__all__ = ["Service", "KNNService", "PairwiseService"]

_service_seq = itertools.count()


# typed knob reads live in config itself now (config.get_float /
# get_int raise LogicError naming the knob AND its env var — the
# ad-hoc parses here used to surface malformed env values as bare
# ValueErrors deep inside construction); these aliases keep the
# serve-local call sites short
_knob_float = config.get_float
_knob_int = config.get_int


def _parse_tenant_weights(spec) -> Optional[dict]:
    """Resolve a tenant-weight spec — ``{name: weight}`` dict, or the
    ``serve_tenant_weights`` knob's ``"name:weight,name:weight"``
    string — into a dict (None/empty = tenancy off)."""
    if spec is None:
        return None
    if isinstance(spec, dict):
        return {str(k): float(v) for k, v in spec.items()} or None
    out = {}
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, w = tok.partition(":")
        try:
            out[name.strip()] = float(w) if sep else 1.0
        except ValueError:
            raise ValueError(
                "serve_tenant_weights: %r is not name:weight" % tok
            ) from None
    return out or None


def _parse_windows(spec) -> tuple:
    """Resolve an SLO-window seconds list (an explicit sequence, or
    the ``serve_slo_windows_s`` knob already parsed by
    :func:`config.get_float_list`) into an ascending float tuple."""
    try:
        out = tuple(sorted(float(tok) for tok in
                           (spec.split(",") if isinstance(spec, str)
                            else spec) if str(tok).strip()))
    except (TypeError, ValueError):
        raise ValueError(
            "serve_slo_windows_s: %r is not a comma-separated number "
            "list" % (spec,)) from None
    expects(len(out) > 0 and all(w > 0 for w in out),
            "serve_slo_windows_s: %r resolves to no positive windows",
            spec)
    return out


def _breaker_from_knobs(name: str, clock) -> Optional[CircuitBreaker]:
    """One breaker per the ``serve_breaker_*`` knobs, or None when both
    trip conditions are knobbed off (the env-level opt-out — a breaker
    that can never open is just overhead).  Shared by the service-level
    breaker and the per-replica breakers."""
    threshold = _knob_int("serve_breaker_threshold")
    window_failures = _knob_int("serve_breaker_window_failures")
    if threshold == 0 and window_failures == 0:
        return None
    return CircuitBreaker(
        name,
        failure_threshold=threshold,
        window=_knob_int("serve_breaker_window"),
        window_failures=window_failures,
        cooldown_s=_knob_float("serve_breaker_cooldown_ms") / 1e3,
        clock=clock)


# -- device functions -------------------------------------------------- #
# module-level + profiled_jit: one executable cache per (fn, shapes,
# statics) across ALL services, with per-bucket hit/miss/compile-seconds
# visible through compile_cache_stats() under this name.  (The kNN
# service deliberately has no such wrapper — see the module doc — its
# cached program is tiled_knn's existing profiled_jit.)
def _pairwise_impl(y, queries, metric):
    return pairwise_distance(queries, y, metric)


_pairwise_device = profiled_jit(
    name="serve_pairwise", static_argnames=("metric",))(_pairwise_impl)
# the donating twin (zero-copy serve path, docs/ZERO_COPY.md): the
# padded batch buffer is CONSUMED by the call and recycled for the
# output.  A separate wrapper (and stats name), not a flag — a donating
# and a non-donating executable must never share a cache slot
_pairwise_device_donated = profiled_jit(
    name="serve_pairwise_donated", static_argnames=("metric",),
    donate_argnames=("queries",))(_pairwise_impl)


class Service:
    """Micro-batching façade over one device function.

    Parameters
    ----------
    execute:
        ``execute(padded_queries) -> pytree`` with batch-rows-leading
        leaves (subclasses bind the pinned operands).
    dim / dtype:
        Query row shape contract; enforced at ``submit``.
    max_batch_rows:
        Top bucket rung = device-call row cap = per-request row cap.
    bucket_rungs / max_wait_ms / queue_cap:
        Shape ladder, micro-batch window, admission cap; each defaults
        to its ``serve_*`` knob in :mod:`raft_tpu.config`.
    retry_policy:
        Optional per-batch :class:`~raft_tpu.comms.resilience.RetryPolicy`
        (watchdog deadline + retries around the device call).
    donate:
        Donate the padded batch buffer to the bucketed executable
        (docs/ZERO_COPY.md): the buffer is serve-internal, so
        recycling it costs nothing and saves one output allocation per
        batch.  Default: on whenever no ``retry_policy`` is set (a
        retry would replay a consumed buffer); pass ``False`` to opt
        out.
    breaker:
        The service circuit breaker
        (:class:`~raft_tpu.serve.resilience.CircuitBreaker`;
        docs/FAULT_MODEL.md "Serving failure model").  Default (None):
        construct one from the ``serve_breaker_*`` config knobs —
        every service is breaker-protected out of the box.  Pass a
        configured instance to tune it, or ``False`` to opt out
        entirely (PR 3's relay-every-failure behavior).
    tenant_weights:
        Multi-tenant traffic shaping (docs/SERVING.md "Traffic
        shaping"): a ``{tenant: weight}`` dict or the knob's
        ``"name:weight,..."`` string.  Each coalesce window is formed
        as a weighted-fair share of the batch across tenants with
        queued work, and each tenant's admission cap is its weight's
        share of ``queue_cap`` — a flooding bulk tenant sheds itself,
        not everyone.  Default: the ``serve_tenant_weights`` knob
        (empty = single-queue serving).
    query_cache_size:
        > 0 enables the :class:`VecCache` query-vector cache
        (:meth:`cache_put` / :meth:`submit_keys`).
    maintenance / maintenance_interval_s:
        Optional background-work callback run on the worker thread
        between batches (see :class:`ServeWorker`) — the ANN service's
        compaction seam.
    start:
        Spawn the worker thread now (False = threadless: tests drive
        :attr:`worker` ``.run_once()`` under an injected ``clock``).
    """

    # sharded-serving contract surface (docs/SERVING.md "Sharded
    # serving"): non-None on services dispatching into a mesh-sharded
    # SPMD program.  Session ``health_check`` reads these to validate a
    # service's mesh assumptions against the (possibly rebuilt) session
    # mesh, and ``RecoveryManager`` triggers ``post_recover``
    # re-partitioning through them.
    axis: Optional[str] = None
    mesh = None

    def __init__(self, name: str, execute: Callable, dim: int,
                 dtype=jnp.float32, *,
                 max_batch_rows: int = 1024,
                 bucket_rungs=None,
                 max_wait_ms: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 retry_policy=None,
                 donate: Optional[bool] = None,
                 breaker=None,
                 tenant_weights=None,
                 query_cache_size: int = 0,
                 maintenance: Optional[Callable[[], None]] = None,
                 maintenance_interval_s: float = 0.05,
                 start: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        expects(dim >= 1, "Service: dim=%d", dim)
        self.name = name
        self.dim = int(dim)
        self.dtype = jnp.dtype(dtype)
        self._execute = execute
        self._clock = clock
        # donation INTENT only (default on): ServeWorker owns the
        # retry-gating rule; the resolved value is read back from the
        # worker below, and subclasses use it to pick their device-fn
        # variant
        donate_intent = True if donate is None else bool(donate)
        if bucket_rungs is None:
            bucket_rungs = config.get("serve_bucket_rungs")
        if max_wait_ms is None:
            max_wait_ms = _knob_float("serve_max_wait_ms")
        if queue_cap is None:
            queue_cap = _knob_int("serve_queue_cap")
        if tenant_weights is None:
            tenant_weights = config.get("serve_tenant_weights")
        tenant_weights = _parse_tenant_weights(tenant_weights)
        self.tenant_weights = tenant_weights
        self.policy = BucketPolicy(
            resolve_rungs(bucket_rungs, int(max_batch_rows)))
        self.batcher = MicroBatcher(
            max_batch_rows=self.policy.max_rows,
            max_wait_s=float(max_wait_ms) / 1e3,
            queue_cap=int(queue_cap), clock=clock, name=name,
            tenant_weights=tenant_weights)
        if breaker is None:
            breaker = _breaker_from_knobs(name, clock)
        elif breaker is False:
            breaker = None
        self.breaker = breaker
        # per-tenant SLO tracker (docs/OBSERVABILITY.md "Flight
        # recorder & request tracing"): latency target +
        # deadline-hit-rate with multi-window burn rates, fed by the
        # worker per terminal request and surfaced through stats()
        self.slo = flight.slo_for(
            name,
            target_s=_knob_float("serve_slo_target_ms") / 1e3,
            objective=_knob_float("serve_slo_objective"),
            windows_s=_parse_windows(
                config.get_float_list("serve_slo_windows_s")),
            clock=clock)
        # fresh exemplars to match the fresh SLO tracker: a rebuilt
        # service under a reused name must not report the dead
        # incarnation's slowest trace_ids (cleared in place — the
        # worker caches the same reservoir object)
        flight.exemplars_for(name).clear()
        self.worker = ServeWorker(name, self.batcher, self.policy,
                                  execute, retry_policy=retry_policy,
                                  donate=donate_intent,
                                  maintenance=maintenance,
                                  maintenance_interval_s=(
                                      maintenance_interval_s),
                                  breaker=breaker,
                                  slo=self.slo,
                                  clock=clock)
        self.donate = self.worker.donate
        self._warmed: Tuple[int, ...] = ()
        self._closed = False
        self._cache_lock = threading.Lock()
        self._cache: Optional[VecCache] = None
        self._cache_state = None
        if query_cache_size > 0:
            self._cache = VecCache(self.dim, int(query_cache_size),
                                   dtype=self.dtype)
            self._cache_state = self._cache.init()
        if start:
            self.worker.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def warmup(self) -> "Service":
        """AOT-precompile every bucket rung through the device function
        (zeros payloads; results discarded after ``block_until_ready``).
        After warmup, steady-state traffic at any admissible shape runs
        entirely on cache hits — assert it via ``compile_cache_stats()``.
        """
        for rung in self.policy.rungs:
            out = self._execute(jnp.zeros((rung, self.dim), self.dtype))
            jax.block_until_ready(out)
        self._warmed = self.policy.rungs
        return self

    @property
    def warmed_rungs(self) -> Tuple[int, ...]:
        return self._warmed

    def is_open(self) -> bool:
        return not self._closed

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission, serve out the queue; True when empty."""
        return self.worker.drain(timeout=timeout)

    # -- recovery seams (raft_tpu/serve/resilience.py) ----------------- #
    def pause(self) -> None:
        """Suspend the service for recovery: new submits shed with
        :class:`~raft_tpu.core.error.ServiceUnavailableError`
        (``reason="recovering"``), batch formation stops, queued
        requests wait.  Reversible (:meth:`resume`) — unlike drain."""
        self.batcher.pause()

    def resume(self) -> None:
        """Re-admit after :meth:`pause`: batch formation restarts (the
        queued backlog first) and the breaker — whose history described
        the pre-recovery world — is reset closed."""
        self.batcher.resume()
        if self.breaker is not None:
            self.breaker.reset()

    def post_recover(self) -> None:
        """Hook run by :class:`~raft_tpu.serve.resilience.RecoveryManager`
        after a communicator/mesh rebuild, before ``warmup()``.  The
        base services pin only immutable operands — nothing to redo;
        :class:`~raft_tpu.serve.ann_service.ANNService` re-publishes
        its ``(index, delta)`` snapshot here, and the sharded services
        re-partition onto the rebuilt mesh (``repartition()``)."""

    # -- shared sharded-recovery plumbing (one copy for KNN and ANN;
    #    docs/SERVING.md "Sharded serving") -------------------------- #
    def _recovery_mesh(self):
        """The mesh ``repartition()`` should re-cut onto when none is
        given: the owning session's rebuilt mesh when it still carries
        our axis (``Comms.serve`` binds ``_session``), else the
        current one (standalone services recover in place)."""
        session = getattr(self, "_session", None)
        comms = getattr(session, "comms", None)
        if comms is not None and self.axis in comms.mesh.axis_names:
            return comms.mesh
        return self.mesh

    def _drop_stale_group_size(self, mesh) -> None:
        """A constructor-pinned hierarchical ``group_size`` that does
        not divide the survivor mesh's axis size must not brick
        recovery (every post-repartition dispatch would raise): drop
        the pin and let ``resolve_group_size`` re-derive the group
        from placement per mesh."""
        g = getattr(self, "_group_size", None)
        if g and int(mesh.shape[self.axis]) % int(g):
            self._group_size = None

    def _record_repartition(self, mesh) -> None:
        _counter("raft_tpu_serve_repartitions_total",
                 "sharded-index re-partitions (shard-loss recovery)",
                 self.name).inc()
        _gauge("raft_tpu_serve_shard_devices",
               "devices the service's sharded index spans (0/absent = "
               "single-device)", self.name).set(
                   int(mesh.shape[self.axis]))
        flight.record("repartition", service=self.name,
                      devices=int(mesh.shape[self.axis]))

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Drain (by default) and stop the worker.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.worker.close(drain=drain, timeout=timeout)

    def __enter__(self) -> "Service":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # submission
    # ------------------------------------------------------------------ #
    def _check_payload(self, queries) -> jnp.ndarray:
        q = jnp.asarray(queries)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2 and q.shape[1] == self.dim,
                "%s.submit: expected (rows, %d) queries, got %r",
                self.name, self.dim, tuple(q.shape))
        return q.astype(self.dtype)

    def submit(self, queries, timeout: Optional[float] = None, *,
               tenant: Optional[str] = None,
               tier: int = 0) -> ServeFuture:
        """Enqueue a query block; returns a future resolving to this
        service's result slice for exactly those rows.

        ``timeout`` is the request's end-to-end deadline in seconds: if
        it expires while the request is still queued, the future fails
        with :class:`~raft_tpu.core.error.CommTimeoutError` instead of
        occupying a batch (deadline-aware shedding).

        ``tenant`` tags the request for weighted-fair traffic shaping
        (None = the default tenant) and ``tier`` is the priority
        override applied before earliest-deadline-first ordering within
        the tenant's share (lower = more urgent; docs/SERVING.md
        "Traffic shaping").

        Unavailability sheds FAST with
        :class:`~raft_tpu.core.error.ServiceUnavailableError` before
        anything is queued: a dead worker thread (the queue would only
        absorb requests nobody serves — restart/recover first), an open
        circuit breaker (``retry_after_s`` carries the cooldown), or a
        recovery in progress.
        """
        expects(not self._closed, "%s.submit: service is closed",
                self.name)
        # payload validation FIRST: a malformed request is the caller's
        # bug and must not consume a half-open probe slot
        q = self._check_payload(queries)
        self._check_available()
        deadline_t = None if timeout is None else self._clock() + timeout
        try:
            fut = self.batcher.submit(q, int(q.shape[0]), deadline_t,
                                      tenant=tenant, tier=tier)
        except ServiceOverloadError as e:
            _counter("raft_tpu_serve_rejected_total",
                     "requests shed by admission control",
                     self.name).inc()
            if e.tenant is not None:
                _tenant_counter("raft_tpu_serve_tenant_rejected_total",
                                "requests shed by admission control, "
                                "per tenant", self.name, e.tenant).inc()
            # sheds precede admission, so no trace exists — a system
            # event keeps them visible in the ordered stream anyway
            flight.record("shed", service=self.name, tenant=e.tenant,
                          reason="overload")
            raise
        _counter("raft_tpu_serve_submitted_total",
                 "admitted requests", self.name).inc()
        _gauge("raft_tpu_serve_queue_depth", "requests queued",
               self.name).set(self.batcher.depth())
        return fut

    def _shed_unavailable(self, message: str, reason: str,
                          retry_after_s: float = 0.0) -> None:
        _counter("raft_tpu_serve_unavailable_total",
                 "requests shed because the service is broken or "
                 "healing (breaker open / dead worker / recovering)",
                 self.name).inc()
        flight.record("shed", service=self.name, reason=reason)
        raise ServiceUnavailableError(message, self.name, reason,
                                      retry_after_s)

    def _check_available(self) -> None:
        """The fail-fast half of admission (docs/FAULT_MODEL.md): a
        request must never be queued into a service that cannot
        possibly serve it."""
        w = self.worker
        if w.dead():
            self._shed_unavailable(
                "%s.submit: worker thread has died — restart() or "
                "recover before resubmitting" % self.name,
                "worker_dead")
        if self.batcher.paused():
            self._shed_unavailable(
                "%s.submit: recovery in progress" % self.name,
                "recovering")
        if self.breaker is not None and not self.breaker.allow():
            half_open = self.breaker.state is BreakerState.HALF_OPEN
            self._shed_unavailable(
                "%s.submit: circuit breaker is %s — back off and "
                "retry" % (self.name,
                           "half-open (probe budget spent)"
                           if half_open else "open"),
                "breaker_half_open" if half_open else "breaker_open",
                self.breaker.retry_after())

    def submit_many(self, blocks: Sequence,
                    timeout: Optional[float] = None, *,
                    tenant: Optional[str] = None,
                    tier: int = 0) -> List[ServeFuture]:
        """Submit several query blocks; one future each, same deadline
        (and the same tenant/tier tags)."""
        return [self.submit(b, timeout=timeout, tenant=tenant,
                            tier=tier) for b in blocks]

    # ------------------------------------------------------------------ #
    # query-vector cache (the dormant cache/VecCache, wired in)
    # ------------------------------------------------------------------ #
    def _require_cache(self) -> VecCache:
        expects(self._cache is not None,
                "%s: no query cache (construct with query_cache_size>0)",
                self.name)
        return self._cache

    def cache_put(self, keys, vectors) -> None:
        """Store query vectors under caller ids for later
        :meth:`submit_keys` (functional :class:`VecCache` state swapped
        under a lock — concurrent submitters stay consistent)."""
        cache = self._require_cache()
        k = jnp.asarray(keys, jnp.int32).ravel()
        v = self._check_payload(vectors)
        expects(k.shape[0] == v.shape[0],
                "%s.cache_put: %d keys for %d vectors", self.name,
                k.shape[0], v.shape[0])
        expects(k.shape[0] == 0 or bool((k >= 0).all()),
                "%s.cache_put: negative keys (the cache reserves -1 "
                "for empty ways)", self.name)
        with self._cache_lock:
            self._cache_state = cache.store_vecs(self._cache_state, k, v)

    def cache_lookup(self, keys) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Fetch cached vectors for ``keys``; returns ``(vectors,
        found)`` and feeds the hit/miss counters."""
        cache = self._require_cache()
        k = jnp.asarray(keys, jnp.int32).ravel()
        with self._cache_lock:
            vecs, found, self._cache_state = cache.get_vecs(
                self._cache_state, k)
        hits = int(found.sum())
        if hits:
            _counter("raft_tpu_serve_query_cache_hits_total",
                     "query-vector cache hits", self.name).inc(hits)
        if hits < k.shape[0]:
            _counter("raft_tpu_serve_query_cache_misses_total",
                     "query-vector cache misses", self.name).inc(
                         k.shape[0] - hits)
        return vecs, found

    def submit_keys(self, keys, timeout: Optional[float] = None
                    ) -> ServeFuture:
        """Submit queries *by cached id* — the repeat-query fast path
        (e.g. a stored user embedding queried on every page view).
        Every key must be cached; missing keys raise
        :class:`LogicError` naming them."""
        k = jnp.asarray(keys, jnp.int32).ravel()
        vecs, found = self.cache_lookup(k)
        if not bool(found.all()):
            missing = [int(x) for x in k[~found]][:16]
            raise LogicError(
                "%s.submit_keys: keys not in the query cache: %r%s"
                % (self.name, missing,
                   "..." if (~found).sum() > 16 else ""))
        return self.submit(vecs, timeout=timeout)

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        """Small live-state dict (health_check embeds it)."""
        out = {
            "open": self.is_open(),
            "worker_started": self.worker.started(),
            "worker_alive": self.worker.is_alive(),
            "queue_depth": self.batcher.depth(),
            "rows_queued": self.batcher.rows_queued(),
            "rungs": list(self.policy.rungs),
            "warmed": bool(self._warmed),
            "paused": self.batcher.paused(),
            # a silently failing compactor/maintenance callback must be
            # visible here, not only as a bare counter
            "last_maintenance_error": self.worker.last_maintenance_error,
            # per-tenant SLO state (hit ratio + multi-window burn) and
            # the slowest-observation exemplars — a p99 complaint
            # starts from stats() and ends at fut.trace() timelines
            "slo": self.slo.snapshot(),
            "exemplars": flight.exemplars_for(self.name).snapshot(),
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.describe()
        if self.tenant_weights:
            depths = self.batcher.tenant_depths()   # one lock pass
            out["tenants"] = {
                name: {"weight": w,
                       "depth": depths.get(name, 0),
                       "cap": self.batcher.tenant_cap(name)}
                for name, w in self.batcher.tenants().items()}
        rs = getattr(self, "_replica_set", None)
        if rs is not None:
            out["replicas"] = rs.describe()
        if self.axis is not None:
            out.update({
                "sharded": True,
                "axis": self.axis,
                "shard_devices": int(self.mesh.shape[self.axis]),
                "merge": getattr(self, "merge", None),
            })
        return out


def _resolve_shard_spec(cls_name: str, mesh, axis, merge):
    """Shared sharded-constructor resolution (KNNService and
    ANNService): default the mesh, default the axis to the mesh's
    first, validate, resolve the merge-topology knob.  One copy of the
    dance — the two services must never skew on it."""
    from raft_tpu.spatial.mnmg_knn import resolve_merge

    if mesh is None:
        from raft_tpu.comms.host_comms import default_mesh

        mesh = default_mesh()
    if axis is None:
        axis = mesh.axis_names[0]
    expects(axis in mesh.axis_names,
            "%s: axis %r not in mesh axes %r", cls_name, axis,
            tuple(mesh.axis_names))
    # registry resolution at CONSTRUCTION: the service pins its merge
    # topology once (tuning table answers per the mesh's device count)
    return mesh, axis, resolve_merge(
        merge, devices=int(mesh.shape[axis]))


class _ShardState(NamedTuple):
    """One immutable sharded-dispatch snapshot: the committed index
    shards and the mesh geometry they were cut for travel TOGETHER —
    a batch reads exactly one of these, so a concurrent
    :meth:`KNNService.repartition` can never pair new shards with the
    old mesh mid-dispatch (the ANNService ``_AnnState`` argument,
    applied to the kNN shard)."""

    index: object       # (rows*size, d) committed NamedSharding array
    n_rows: int         # real rows (the mask bound)
    mesh: object
    axis: str


class KNNService(Service):
    """Micro-batched :func:`brute_force_knn` over one pinned index
    partition — or, with ``axis=``, one pjit'd SPMD search over a
    mesh-sharded index (docs/SERVING.md "Sharded serving").

    ``submit((n_i, d))`` futures resolve to ``(distances, indices)`` of
    shape ``(n_i, k)``.  Single-device: bit-identical to the unbatched
    ``brute_force_knn(index, queries, k)`` call (pad rows are zeros and
    every row's result depends only on its own query row).  Sharded:
    bit-identical to :func:`~raft_tpu.spatial.mnmg_knn.mnmg_knn` at
    the same topology, and index-identical to the single-device call
    up to distance-tie order (the merge re-selects across shard-local
    selections; on exact distance ties the survivor may differ).

    Sharded parameters
    ------------------
    mesh / axis:
        Shard the index rows over ``axis`` of ``mesh``
        (:func:`~raft_tpu.spatial.mnmg_knn.shard_knn_index` commits
        the shards ONCE at construction; batches reuse them with no
        per-call resharding).  ``axis`` alone resolves the default
        mesh; session-registered services
        (``Comms.serve(kind="knn", axis=...)``) default to the
        session mesh.
    merge:
        Cross-shard top-k merge topology (``allgather`` | ``ring`` |
        ``hierarchical``); None resolves the ``mnmg_merge`` knob.
    group_size:
        Hierarchical host-group size; None auto-resolves from device
        placement per mesh.

    On shard loss, :meth:`repartition` (driven by ``post_recover``
    during the :class:`~raft_tpu.serve.resilience.RecoveryManager`
    sequence) re-partitions the full index over the surviving
    sub-mesh and the follow-up ``warmup()`` rebuilds every per-rung
    sharded executable.

    Replica parameters (docs/SERVING.md "Traffic shaping")
    ------------------------------------------------------
    replicas:
        Build this many replicas of the index over **disjoint**
        sub-meshes of ``mesh`` (each replica itself sharded over its
        group when the group holds more than one device), dispatched
        through a :class:`~raft_tpu.serve.replicas.ReplicaSet`:
        rotation with per-replica circuit breakers (a tripped replica
        drops out instead of tripping the service) and **hedged
        re-dispatch** of straggling batches with first-result-wins
        resolution and loser cancellation.  Forces ``donate=False``
        (a hedge must be able to re-dispatch the padded buffer).
        Mutually composes with ``mesh``/``axis``/``merge``: they
        describe the parent span the replicas are cut from.
    hedge_ms:
        Fixed hedge threshold in milliseconds; None resolves the
        ``serve_hedge_ms`` knob (0 = adaptive per-rung p99 ×
        ``serve_hedge_factor``, floored at ``serve_hedge_min_ms``).
    """

    def __init__(self, index, k: int,
                 metric: DistanceType = DistanceType.L2Expanded,
                 tile_n: int = 8192, precision: str = "highest",
                 mesh=None, axis: Optional[str] = None,
                 merge: Optional[str] = None,
                 group_size: Optional[int] = None,
                 replicas: Optional[int] = None,
                 hedge_ms: Optional[float] = None,
                 name: Optional[str] = None, **opts):
        index = jnp.asarray(index)
        expects(index.ndim == 2, "KNNService: (n, d) index required")
        expects(1 <= k <= index.shape[0],
                "KNNService: k=%d out of range for n_index=%d",
                k, index.shape[0])
        self.index = index
        self.k = int(k)
        self.metric = metric
        self._tile_n = int(tile_n)
        self._precision = precision
        self._group_size = group_size
        self._spmd: Optional[_ShardState] = None
        self._replica_set = None
        # resolved early (ANNService precedent): replica breakers and
        # metric labels need the name before Service.__init__ runs
        name = name or "knn%d" % next(_service_seq)
        self.name = name
        if replicas is not None:
            expects(int(replicas) >= 2,
                    "KNNService: replicas=%d (need >= 2; one replica "
                    "is just a [sharded] service)", int(replicas))
            mesh, axis, self.merge = _resolve_shard_spec(
                "KNNService", mesh, axis, merge)
            if hedge_ms is None:
                hedge_ms = _knob_float("serve_hedge_ms")
            self._hedge_s = (None if float(hedge_ms) <= 0.0
                             else float(hedge_ms) / 1e3)
            self._hedge_factor = _knob_float("serve_hedge_factor")
            self._hedge_min_s = _knob_float("serve_hedge_min_ms") / 1e3
            self._n_replicas = int(replicas)
            self._replica_axis = axis
            self._replica_parent = mesh
            # hedged re-dispatch must be able to replay the padded
            # buffer on a second replica — same rule as a RetryPolicy
            opts["donate"] = False
            self._replica_set = self._build_replica_set(
                mesh, axis, self._n_replicas,
                opts.get("clock", time.monotonic))
        elif mesh is not None or axis is not None:
            mesh, axis, self.merge = _resolve_shard_spec(
                "KNNService", mesh, axis, merge)
            self._shard_to(mesh, axis)

        def execute(padded):
            rs = self._replica_set     # ONE snapshot per batch
            if rs is not None:
                # rotation + per-replica breakers + hedged dispatch
                # (raft_tpu/serve/replicas.py); the returned result is
                # already device-ready (the winning arm blocked)
                return rs.run(padded)
            spmd = self._spmd          # ONE snapshot per batch
            if spmd is not None:
                # ONE SPMD program per bucket rung: per-shard search,
                # on-device id translation, on-device top-k merge —
                # 0 host-staged bytes (docs/ZERO_COPY.md), donation
                # routed into the sharded donating twin
                from raft_tpu.spatial.mnmg_knn import mnmg_knn

                return mnmg_knn(spmd.index, padded, self.k,
                                metric=self.metric, mesh=spmd.mesh,
                                axis=spmd.axis, n_rows=spmd.n_rows,
                                tile_n=self._tile_n,
                                precision=self._precision,
                                merge=self.merge,
                                group_size=self._group_size,
                                donate_queries=self.donate)
            # eager on purpose: bit-identical to the unbatched call
            # (module doc); the scan inside is the per-bucket cached
            # program.  donate_queries routes the padded buffer into
            # the scan's donating executable twin (identical program,
            # recycled input — docs/ZERO_COPY.md); self.donate is set
            # by Service.__init__ before any batch can run
            return brute_force_knn(self.index, padded, self.k,
                                   metric=self.metric, tile_n=tile_n,
                                   precision=precision,
                                   donate_queries=self.donate)

        super().__init__(
            name, execute,
            dim=index.shape[1], dtype=index.dtype, **opts)
        if self.axis is not None:   # gauge deferred until named
            _gauge("raft_tpu_serve_shard_devices",
                   "devices the service's sharded index spans "
                   "(0/absent = single-device)", self.name).set(
                       int(self.mesh.shape[self.axis]))

    # -- sharded serving (docs/SERVING.md "Sharded serving") ----------- #
    @property
    def mesh(self):
        return self._spmd.mesh if self._spmd is not None else None

    @property
    def axis(self) -> Optional[str]:
        return self._spmd.axis if self._spmd is not None else None

    # -- replica groups + hedged dispatch (docs/SERVING.md "Traffic
    #    shaping"; raft_tpu/serve/replicas.py) ----------------------- #
    def _replica_group_size(self, mesh) -> Optional[int]:
        """The pinned hierarchical group size, dropped when it does not
        divide a replica sub-mesh's axis (the `_drop_stale_group_size`
        rule applied per group)."""
        g = self._group_size
        if g and int(mesh.shape[self._replica_axis]) % int(g):
            return None
        return g

    def _build_replica_set(self, parent_mesh, axis: str, n: int, clock):
        """Cut ``parent_mesh`` into ``n`` disjoint sub-meshes, commit a
        full copy of the index (row-sharded) to each, and wrap them in
        a :class:`~raft_tpu.serve.replicas.ReplicaSet` with fresh
        per-replica breakers."""
        from raft_tpu.serve.replicas import ReplicaSet, split_mesh
        from raft_tpu.spatial.mnmg_knn import mnmg_knn, shard_knn_index

        members = []
        for m in split_mesh(parent_mesh, axis, n):
            index_p, n_rows = shard_knn_index(self.index, m, axis)
            state = _ShardState(index_p, n_rows, m, axis)

            def exec_replica(padded, st=state):
                # donation stays off: a hedge re-dispatches the SAME
                # padded buffer on another replica
                return mnmg_knn(st.index, padded, self.k,
                                metric=self.metric, mesh=st.mesh,
                                axis=st.axis, n_rows=st.n_rows,
                                tile_n=self._tile_n,
                                precision=self._precision,
                                merge=self.merge,
                                group_size=self._replica_group_size(
                                    st.mesh),
                                donate_queries=False)

            members.append((m, exec_replica))
        breakers = [_breaker_from_knobs("%s/r%d" % (self.name, i),
                                        clock)
                    for i in range(len(members))]
        return ReplicaSet(self.name, members,
                          hedge_s=self._hedge_s,
                          hedge_factor=self._hedge_factor,
                          hedge_min_s=self._hedge_min_s,
                          breakers=breakers, clock=clock)

    def replica_device_ids(self) -> Optional[set]:
        """Device ids the replica set spans (None when not replicated);
        session ``health_check`` validates them against the current
        mesh."""
        rs = self._replica_set
        return rs.device_ids() if rs is not None else None

    def rebuild_replicas(self, mesh=None) -> bool:
        """Re-cut the replica groups over ``mesh`` (default: the owning
        session's current mesh) — the replica-loss recovery lever.  A
        survivor mesh too small for 2 replicas degrades to plain
        sharded serving over the whole mesh (capacity over redundancy;
        a later rebuild on a grown mesh restores the replicas).  Fresh
        per-replica breakers — the old failure history described the
        pre-recovery world.  Call ``warmup()`` after.  True when the
        mesh changed."""
        expects(self._replica_set is not None or self._spmd is not None,
                "%s.rebuild_replicas: service was not built with "
                "replicas", self.name)
        if mesh is None:
            session = getattr(self, "_session", None)
            comms = getattr(session, "comms", None)
            if (comms is not None
                    and self._replica_axis in comms.mesh.axis_names):
                mesh = comms.mesh
            else:
                mesh = self._replica_parent
        changed = mesh is not self._replica_parent
        n = min(self._n_replicas, int(mesh.devices.size))
        if n >= 2:
            self._replica_parent = mesh
            self._spmd = None
            self._replica_set = self._build_replica_set(
                mesh, self._replica_axis, n, self._clock)
        else:
            # survivors cannot host two disjoint replicas: serve the
            # whole (1-device) mesh sharded, un-replicated
            self._replica_set = None
            self._replica_parent = mesh
            self._shard_to(mesh, self._replica_axis)
        if changed:
            self._record_repartition_replicas(mesh)
        return changed

    def _record_repartition_replicas(self, mesh) -> None:
        _counter("raft_tpu_serve_repartitions_total",
                 "sharded-index re-partitions (shard-loss recovery)",
                 self.name).inc()
        _gauge("raft_tpu_serve_shard_devices",
               "devices the service's sharded index spans (0/absent = "
               "single-device)", self.name).set(
                   int(mesh.devices.size))
        flight.record("repartition", service=self.name,
                      devices=int(mesh.devices.size),
                      replicas=(len(self._replica_set.replicas)
                                if self._replica_set is not None else 0))

    def warmup(self) -> "Service":
        rs = self._replica_set
        if rs is None:
            return super().warmup()
        # every replica sub-mesh compiles its own per-rung executables
        # — hedged dispatch may route any rung to any replica, so the
        # zero-steady-state-compiles proof needs the full product
        for rung in self.policy.rungs:
            rs.warm(jnp.zeros((rung, self.dim), self.dtype))
        self._warmed = self.policy.rungs
        return self

    def _shard_to(self, mesh, axis: str) -> None:
        """(Re-)partition the pinned index rows over ``axis`` and
        commit the shards to the mesh.  The swap is ONE reference
        assignment of an immutable :class:`_ShardState` — a batch
        dispatching concurrently reads either the old or the new
        snapshot whole, never new shards with the old mesh."""
        from raft_tpu.spatial.mnmg_knn import shard_knn_index

        index_p, n_rows = shard_knn_index(self.index, mesh, axis)
        self._spmd = _ShardState(index_p, n_rows, mesh, axis)
        if "name" in self.__dict__:   # first call precedes naming
            _gauge("raft_tpu_serve_shard_devices",
                   "devices the service's sharded index spans "
                   "(0/absent = single-device)", self.name).set(
                       int(mesh.shape[axis]))

    def repartition(self, mesh=None) -> bool:
        """Re-partition the index rows over ``mesh`` (default: the
        owning session's current mesh) — the shard-loss recovery lever:
        the lost shard's rows redistribute across the surviving
        sub-mesh, exactly (the full index is re-sharded from the
        pinned source array).  Call ``warmup()`` after — the sharded
        executables are mesh-specific.  True when the mesh changed."""
        expects(self.axis is not None,
                "%s.repartition: service is not sharded", self.name)
        mesh = self._recovery_mesh() if mesh is None else mesh
        expects(self.axis in mesh.axis_names,
                "%s.repartition: replacement mesh lacks axis %r",
                self.name, self.axis)
        if mesh is self.mesh:
            return False
        self._drop_stale_group_size(mesh)
        self._shard_to(mesh, self.axis)
        self._record_repartition(mesh)
        return True

    def post_recover(self) -> None:
        """Re-partition onto the rebuilt session mesh after a
        communicator recovery (RecoveryManager step 4; the follow-up
        ``warmup()`` rebuilds the sharded/replicated executables).
        Keyed off the CONSTRUCTOR's replica intent, not the current
        replica set: a service degraded to unreplicated by a tiny
        survivor mesh must regain its replicas when a later recovery
        regrows the mesh."""
        if getattr(self, "_n_replicas", 0):
            self.rebuild_replicas()
        elif self.axis is not None:
            self.repartition()


class PairwiseService(Service):
    """Micro-batched :func:`pairwise_distance` against one pinned
    reference matrix; futures resolve to the ``(n_i, n_y)`` block."""

    def __init__(self, y,
                 metric: DistanceType = DistanceType.L2Expanded,
                 name: Optional[str] = None, **opts):
        y = jnp.asarray(y)
        expects(y.ndim == 2, "PairwiseService: (n, d) reference required")
        self.y = y
        self.metric = metric

        def execute(padded):
            fn = (_pairwise_device_donated if self.donate
                  else _pairwise_device)
            return fn(self.y, padded, metric=self.metric)

        super().__init__(
            name or "pairwise%d" % next(_service_seq), execute,
            dim=y.shape[1], dtype=y.dtype, **opts)
