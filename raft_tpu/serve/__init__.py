"""Serving layer: dynamic micro-batching query engine (docs/SERVING.md).

The request path in front of the hot primitives: concurrent callers
submit small query blocks; a per-service worker coalesces them into one
padded device call per shape bucket, so

- XLA compile-cache cardinality is bounded (and pre-warmed) by the
  bucket ladder (:mod:`~raft_tpu.serve.bucketing`),
- device efficiency comes from batch fill rather than per-call
  dispatch (:mod:`~raft_tpu.serve.batcher`),
- overload is shed at admission and deadlines expire in-queue
  (:mod:`~raft_tpu.serve.scheduler`),
- facades own warmup / drain / close lifecycle and the optional
  query-vector cache (:mod:`~raft_tpu.serve.service`),
- the native IVF quantizers are served with recall-targeted nprobe
  dispatch and streaming ingestion + worker-loop compaction
  (:mod:`~raft_tpu.serve.ann_service`),
- the serving failure contract — serve-seam fault injection, per-
  service circuit breaker, recovery orchestration, degraded-mode
  dispatch — lives in :mod:`~raft_tpu.serve.resilience`
  (docs/FAULT_MODEL.md "Serving failure model"),
- traffic shaping — multi-tenant weighted-fair admission and EDF
  ordering live in :mod:`~raft_tpu.serve.batcher`; replica groups over
  disjoint sub-meshes with hedged re-dispatch of straggling batches
  live in :mod:`~raft_tpu.serve.replicas` (docs/SERVING.md "Traffic
  shaping"),
- the live ops plane — an embedded jax-free HTTP endpoint
  (``/metrics`` / ``/healthz`` / ``/statusz`` / ``/debug/*``) lives in
  :mod:`~raft_tpu.serve.opsplane`, and the anomaly sentinel that
  watches the recorded vitals and flips it degraded lives in
  :mod:`~raft_tpu.serve.sentinel` (docs/OBSERVABILITY.md "Ops
  plane").

Every layer also records into the flight recorder
(:mod:`raft_tpu.core.flight`; docs/OBSERVABILITY.md "Flight recorder &
request tracing"): each admitted request carries a trace_id and
``ServeFuture.trace()`` returns its complete timeline; breaker trips
and recoveries capture black-box dumps; every service tracks a
per-tenant SLO with burn rates and slowest-K exemplars.

Session integration: ``Comms.serve(...)`` constructs and registers a
service; ``health_check()`` reports live services (breaker state and
maintenance failures included), ``self_heal()`` recovers them, and
``destroy()`` drains them before comms teardown.
"""

from raft_tpu.serve.ann_service import ANNService  # noqa: F401
from raft_tpu.serve.batcher import MicroBatcher, ServeFuture  # noqa: F401
from raft_tpu.serve.opsplane import OpsPlane  # noqa: F401
from raft_tpu.serve.sentinel import AnomalySentinel  # noqa: F401
from raft_tpu.serve.bucketing import (  # noqa: F401
    BucketPolicy,
    coalesce,
    pad_rows,
    resolve_rungs,
    split_rows,
)
from raft_tpu.serve.replicas import (  # noqa: F401
    ReplicaFaultInjector,
    ReplicaSet,
    inject_replica,
    split_mesh,
)
from raft_tpu.serve.resilience import (  # noqa: F401
    BreakerState,
    CircuitBreaker,
    RecoveryManager,
    ServeFaultInjector,
    inject_worker,
)
from raft_tpu.serve.scheduler import ServeWorker  # noqa: F401
from raft_tpu.serve.service import (  # noqa: F401
    KNNService,
    PairwiseService,
    Service,
)

__all__ = [
    "BucketPolicy", "resolve_rungs", "pad_rows", "coalesce", "split_rows",
    "MicroBatcher", "ServeFuture", "ServeWorker",
    "Service", "KNNService", "PairwiseService", "ANNService",
    "BreakerState", "CircuitBreaker", "RecoveryManager",
    "ServeFaultInjector", "inject_worker",
    "ReplicaSet", "ReplicaFaultInjector", "inject_replica", "split_mesh",
    "OpsPlane", "AnomalySentinel",
]
