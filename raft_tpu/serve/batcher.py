"""Thread-safe micro-batching request queue.

One :class:`MicroBatcher` sits between many submitter threads and one
worker (:class:`raft_tpu.serve.scheduler.ServeWorker`).  Submitters
enqueue :class:`_Request` objects and immediately get a
:class:`ServeFuture`; the worker pulls *batches* formed under a simple
coalescing policy:

- dispatch as soon as ``max_batch_rows`` payload rows are queued, or
- when the oldest queued request has waited ``max_wait_s`` (the
  micro-batching window: latency ceiling a lone request pays to give
  co-batched company a chance to arrive), or
- immediately while draining (flush — nobody new is coming).

Admission control happens at ``submit``: beyond ``queue_cap`` queued
requests the submitter gets :class:`ServiceOverloadError` *now* instead
of a silently unbounded queue (shed, don't buffer — the queue would
otherwise absorb the whole overload as latency).

The clock is injectable (``clock=time.monotonic`` by default — note the
function object is the default, the library never calls a wall clock
ad hoc): deterministic tests drive a fake clock and the non-blocking
:meth:`MicroBatcher.take`; production workers block in
:meth:`MicroBatcher.wait_for_batch`.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, List, Optional

from raft_tpu.core.error import (
    CommTimeoutError,
    LogicError,
    ServiceOverloadError,
    expects,
)

__all__ = ["ServeFuture", "MicroBatcher"]


class ServeFuture:
    """Completion handle for one submitted request.

    A minimal future (no cancellation, no callbacks): the worker thread
    resolves it exactly once with a result or an exception; any number
    of threads may :meth:`result` / :meth:`wait` on it.
    """

    __slots__ = ("_event", "_result", "_error", "_service")

    def __init__(self, service: str = "serve"):
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._service = service

    # -- worker side --------------------------------------------------- #
    def _set_result(self, value: Any) -> None:
        self._result = value
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    # -- caller side --------------------------------------------------- #
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _unresolved(self, timeout: Optional[float]) -> CommTimeoutError:
        # the deadline taxonomy everywhere else (queue expiry, watchdog,
        # close) raises CommTimeoutError — a caller-side wait blowing
        # its budget is the same failure class, not a bare TimeoutError
        return CommTimeoutError(
            "serve future for service %r unresolved after waiting %s"
            % (self._service,
               "%.3fs" % timeout if timeout is not None else "forever"))

    def result(self, timeout: Optional[float] = None) -> Any:
        """The request's result; raises the request's failure, or
        :class:`~raft_tpu.core.error.CommTimeoutError` (naming the
        service and the wait) if unresolved within ``timeout``."""
        if not self._event.wait(timeout):
            raise self._unresolved(timeout)
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise self._unresolved(timeout)
        return self._error


class _Request:
    """One queued query block (rows of one submitter's array)."""

    __slots__ = ("payload", "rows", "enqueue_t", "deadline_t", "future",
                 "requeued")

    def __init__(self, payload, rows: int, enqueue_t: float,
                 deadline_t: Optional[float], service: str = "serve"):
        self.payload = payload
        self.rows = rows
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        self.future = ServeFuture(service)
        # the at-most-once recovery re-enqueue mark (scheduler._fail
        # _batch): a rider whose batch died while the breaker tripped is
        # put back exactly once; a second failure relays the error
        self.requeued = False


class MicroBatcher:
    """Coalescing request queue (see module doc for the policy).

    Parameters
    ----------
    max_batch_rows:
        Payload-row dispatch threshold AND per-request row cap (a
        request must fit one batch whole — results split per request,
        never mid-request).
    max_wait_s:
        Micro-batching window measured from the oldest queued request.
    queue_cap:
        Admission cap in *requests* (the reference point operators
        reason about: one queue slot = one caller waiting).
    clock:
        Monotonic-seconds source; injectable for deterministic tests.
    """

    def __init__(self, max_batch_rows: int, max_wait_s: float,
                 queue_cap: int,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "serve"):
        expects(max_batch_rows >= 1,
                "MicroBatcher: max_batch_rows=%d", max_batch_rows)
        expects(max_wait_s >= 0.0,
                "MicroBatcher: max_wait_s=%r", max_wait_s)
        expects(queue_cap >= 1, "MicroBatcher: queue_cap=%d", queue_cap)
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_s)
        self.queue_cap = int(queue_cap)
        self.name = str(name)
        self._clock = clock
        self._cond = threading.Condition()
        self._q: "collections.deque[_Request]" = collections.deque()
        self._rows_queued = 0
        self._paused = False
        self._draining = False
        self._stopped = False

    # ------------------------------------------------------------------ #
    # submitter side
    # ------------------------------------------------------------------ #
    def submit(self, payload, rows: int,
               deadline_t: Optional[float] = None) -> ServeFuture:
        """Enqueue one request; returns its future.

        Raises :class:`ServiceOverloadError` at the admission cap and
        :class:`LogicError` once draining/stopped (a closed service
        must fail loudly, not buffer into a queue nobody serves).
        """
        expects(1 <= rows <= self.max_batch_rows,
                "submit: %d rows outside [1, max_batch_rows=%d] — a "
                "request must fit one batch whole", rows,
                self.max_batch_rows)
        req = _Request(payload, rows, self._clock(), deadline_t,
                       self.name)
        with self._cond:
            if self._draining or self._stopped:
                raise LogicError(
                    "submit: service is draining/closed and no longer "
                    "accepts requests")
            if len(self._q) >= self.queue_cap:
                raise ServiceOverloadError(
                    "serve queue over admission cap; shed and retry "
                    "with backoff", len(self._q), self.queue_cap)
            self._q.append(req)
            self._rows_queued += req.rows
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def rows_queued(self) -> int:
        with self._cond:
            return self._rows_queued

    def empty(self) -> bool:
        with self._cond:
            return not self._q

    def draining(self) -> bool:
        """Whether admission has stopped (drain/close in progress) —
        maintenance work (e.g. compaction) should not start once the
        service is winding down."""
        with self._cond:
            return self._draining

    def paused(self) -> bool:
        """Whether batch formation is paused (recovery in progress)."""
        with self._cond:
            return self._paused

    # ------------------------------------------------------------------ #
    # recovery seams (raft_tpu/serve/resilience.py)
    # ------------------------------------------------------------------ #
    def pause(self) -> None:
        """Stop forming batches (recovery in progress): queued requests
        stay queued, the worker idles.  Unlike :meth:`begin_drain` this
        is reversible (:meth:`resume`); the service façade sheds *new*
        submits with ``ServiceUnavailableError`` while paused."""
        with self._cond:
            self._paused = True
            self._cond.notify_all()

    def resume(self) -> None:
        """Re-admit after a pause: batch formation restarts and the
        queued backlog (including recovery re-enqueues) dispatches."""
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def requeue(self, reqs: List[_Request]) -> bool:
        """Put already-admitted requests back at the FRONT of the queue
        (recovery re-enqueue: riders of a batch that died while the
        breaker tripped are served after recovery instead of lost).
        Bypasses the admission cap and the drain gate — these requests
        were admitted once and must resolve exactly once.  Returns False
        (caller must fail the futures instead) once the queue is
        stopped: after :meth:`shutdown` nobody will ever serve them."""
        with self._cond:
            if self._stopped:
                return False
            for req in reversed(reqs):
                self._q.appendleft(req)
                self._rows_queued += req.rows
            self._cond.notify_all()
        return True

    # ------------------------------------------------------------------ #
    # worker side
    # ------------------------------------------------------------------ #
    def _pop_batch_locked(self) -> List[_Request]:
        batch: List[_Request] = []
        rows = 0
        while self._q and rows + self._q[0].rows <= self.max_batch_rows:
            req = self._q.popleft()
            self._rows_queued -= req.rows
            rows += req.rows
            batch.append(req)
        return batch

    def _ready_locked(self, now: float) -> bool:
        if not self._q:
            return False
        if self._draining or self._stopped:
            return True
        if self._paused:
            return False
        if self._rows_queued >= self.max_batch_rows:
            return True
        return (now - self._q[0].enqueue_t) >= self.max_wait_s

    def take(self) -> Optional[List[_Request]]:
        """Non-blocking: a batch if the policy says dispatch now, else
        None.  The deterministic-test entry point (fake clock + manual
        worker stepping); also used by drain's inline fallback."""
        with self._cond:
            if not self._ready_locked(self._clock()):
                return None
            return self._pop_batch_locked()

    def wait_for_batch(self, timeout: Optional[float] = None
                       ) -> Optional[List[_Request]]:
        """Blocking: the next batch, or None once stopped and empty
        (the worker loop's exit signal).

        ``timeout`` bounds the wait: an empty list is returned when it
        elapses with no batch ready — the worker loop's maintenance
        poll (periodic compaction must get the thread even while the
        queue idles; ``[]`` is "no work yet", distinct from the None
        exit signal)."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                if self._ready_locked(self._clock()):
                    return self._pop_batch_locked()
                if self._stopped and not self._q:
                    return None
                poll = None
                if deadline is not None:
                    poll = deadline - self._clock()
                    if poll <= 0:
                        return []
                if self._q and not self._paused:
                    remaining = max(1e-3,
                                    self._q[0].enqueue_t + self.max_wait_s
                                    - self._clock())
                    self._cond.wait(timeout=remaining if poll is None
                                    else min(remaining, poll))
                else:
                    # empty — or paused for recovery: an overdue head
                    # request must not turn this into a 1 kHz spin;
                    # resume() notifies, so the wake-up is immediate
                    self._cond.wait(timeout=poll)

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def begin_drain(self) -> None:
        """Stop admitting; flush queued requests immediately (no point
        holding the micro-batch window open — nobody new is coming).
        Overrides a recovery pause: drain must serve (or fail) the
        queue out, never hold it hostage to a recovery that will not
        finish."""
        with self._cond:
            self._draining = True
            self._paused = False
            self._cond.notify_all()

    def shutdown(self) -> List[_Request]:
        """Stop the queue for good; returns any requests still queued
        (a non-draining close must fail them, never strand their
        futures).  After shutdown ``wait_for_batch`` returns None."""
        with self._cond:
            self._draining = True
            self._stopped = True
            leftovers = list(self._q)
            self._q.clear()
            self._rows_queued = 0
            self._cond.notify_all()
        return leftovers
