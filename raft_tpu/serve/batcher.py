"""Thread-safe micro-batching request queue with traffic shaping.

One :class:`MicroBatcher` sits between many submitter threads and one
worker (:class:`raft_tpu.serve.scheduler.ServeWorker`).  Submitters
enqueue :class:`_Request` objects and immediately get a
:class:`ServeFuture`; the worker pulls *batches* formed under a simple
coalescing policy:

- dispatch as soon as ``max_batch_rows`` payload rows are queued, or
- when the oldest queued request has waited ``max_wait_s`` (the
  micro-batching window: latency ceiling a lone request pays to give
  co-batched company a chance to arrive), or
- immediately while draining (flush — nobody new is coming).

**Multi-tenant weighted-fair shaping** (docs/SERVING.md "Traffic
shaping"): requests are tagged with a tenant name at ``submit``; each
tenant owns its own queue, and every coalesce window is formed by
**deficit round robin** — tenant *t* with weight ``w_t`` earns a
per-window quantum of ``max_batch_rows * w_t / W`` rows (W = total
weight of tenants *with queued work*, so an idle tenant's share is
redistributed by construction), carried as a deficit across windows
so a request bigger than one share never starves.  A backlogged bulk
tenant's service rate is therefore *bounded by its weight share per
window* — its surplus waits in its own queue instead of inflating the
shared batch's execution time, which is what keeps the interactive
class's latency near its solo value under bulk saturation.  Admission
splits the same way — each tenant's cap is its weight's share of
``queue_cap`` — so a flood sheds the flooding tenant, not everyone.

**Deadline-aware ordering**: within a tenant's share, requests
dispatch earliest-deadline-first (EDF) rather than FIFO — when
deadlines vary, EDF strictly dominates FIFO on deadline hit rate.  An
explicit priority ``tier`` overrides deadlines (lower tier = more
urgent; requests without a deadline order after all deadlines of their
tier, FIFO among themselves).

Admission control happens at ``submit``: beyond the tenant's share of
``queue_cap`` (or the global cap) the submitter gets
:class:`ServiceOverloadError` *now* — naming the tenant and carrying a
``retry_after_s`` queue-drain estimate — instead of a silently
unbounded queue (shed, don't buffer — the queue would otherwise absorb
the whole overload as latency).

**Request tracing** (docs/OBSERVABILITY.md "Flight recorder & request
tracing"): every admitted request is assigned a process-unique
``trace_id`` and a :class:`~raft_tpu.core.flight.Trace` at admission —
the ``admitted`` event carries the tenant's DRR share context (weight,
queue depth, cap) so a later queue-wait number can be attributed to
the share that produced it — and
:meth:`ServeFuture.trace` hands the complete per-request timeline
back after resolution.

The clock is injectable (``clock=time.monotonic`` by default — note the
function object is the default, the library never calls a wall clock
ad hoc): deterministic tests drive a fake clock and the non-blocking
:meth:`MicroBatcher.take`; production workers block in
:meth:`MicroBatcher.wait_for_batch`.
"""

from __future__ import annotations

import collections
import heapq
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from raft_tpu.core import flight
from raft_tpu.core.error import (
    CommTimeoutError,
    LogicError,
    ServiceOverloadError,
    expects,
)

__all__ = ["ServeFuture", "MicroBatcher"]

DEFAULT_TENANT = "default"


class ServeFuture:
    """Completion handle for one submitted request.

    A minimal future (no cancellation, no callbacks): the worker thread
    resolves it exactly once with a result or an exception; any number
    of threads may :meth:`result` / :meth:`wait` on it.
    """

    __slots__ = ("_event", "_result", "_error", "_service", "_trace")

    def __init__(self, service: str = "serve", trace=None):
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._service = service
        self._trace = trace

    # -- worker side --------------------------------------------------- #
    def _set_result(self, value: Any) -> None:
        self._result = value
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    # -- caller side --------------------------------------------------- #
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _unresolved(self, timeout: Optional[float]) -> CommTimeoutError:
        # the deadline taxonomy everywhere else (queue expiry, watchdog,
        # close) raises CommTimeoutError — a caller-side wait blowing
        # its budget is the same failure class, not a bare TimeoutError
        return CommTimeoutError(
            "serve future for service %r unresolved after waiting %s"
            % (self._service,
               "%.3fs" % timeout if timeout is not None else "forever"))

    def result(self, timeout: Optional[float] = None) -> Any:
        """The request's result; raises the request's failure, or
        :class:`~raft_tpu.core.error.CommTimeoutError` (naming the
        service and the wait) if unresolved within ``timeout``."""
        if not self._event.wait(timeout):
            raise self._unresolved(timeout)
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise self._unresolved(timeout)
        return self._error

    def trace(self):
        """The request's :class:`~raft_tpu.core.flight.Trace` — the
        complete per-request timeline (admitted → queue wait → batch
        id/rung → hedge outcome → execute bracket → terminal), built
        as the request moves through the pipeline.  Complete once the
        future is resolved (``trace().terminal()`` names how); None
        when flight recording is disabled (``RAFT_TPU_FLIGHT=0``)."""
        return self._trace


class _Request:
    """One queued query block (rows of one submitter's array)."""

    __slots__ = ("payload", "rows", "enqueue_t", "deadline_t", "future",
                 "requeued", "tenant", "tier", "seq", "taken", "trace")

    def __init__(self, payload, rows: int, enqueue_t: float,
                 deadline_t: Optional[float], service: str = "serve",
                 tenant: str = DEFAULT_TENANT, tier: int = 0):
        self.payload = payload
        self.rows = rows
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        # the request-scoped trace (None when flight recording is off):
        # assigned HERE so the trace_id exists before any queue state
        # does, and handed to the future for ServeFuture.trace()
        self.trace = flight.default_recorder().new_trace(service, tenant)
        self.future = ServeFuture(service, trace=self.trace)
        self.tenant = tenant
        self.tier = tier
        # FIFO tie-break within (tier, deadline); assigned at admission
        self.seq = 0
        # popped-from-queue mark, read by the lazy arrival-order sweep
        self.taken = False
        # the at-most-once recovery re-enqueue mark (scheduler._fail
        # _batch): a rider whose batch died while the breaker tripped is
        # put back exactly once; a second failure relays the error
        self.requeued = False


class _TenantQueue:
    """One tenant's queue: a requeued-first deque (recovery re-enqueues
    are served before fresh traffic) plus an EDF heap ordered by
    (tier, deadline, seq) — no deadline sorts after every deadline of
    its tier, and seq keeps FIFO among equals.  ``deficit`` is the
    tenant's deficit-round-robin credit: unused quota carried across
    windows (so a request bigger than one window's share is never
    starved), reset whenever the queue empties."""

    __slots__ = ("weight", "requeued", "heap", "rows", "depth",
                 "deficit")

    def __init__(self, weight: float):
        self.weight = float(weight)
        self.requeued: "collections.deque[_Request]" = collections.deque()
        self.heap: list = []
        self.rows = 0
        self.depth = 0
        self.deficit = 0.0

    def push(self, req: _Request) -> None:
        key = (req.tier,
               math.inf if req.deadline_t is None else req.deadline_t,
               req.seq)
        heapq.heappush(self.heap, (key, req))
        self.rows += req.rows
        self.depth += 1

    def push_front(self, req: _Request) -> None:
        self.requeued.appendleft(req)
        self.rows += req.rows
        self.depth += 1

    def peek(self) -> Optional[_Request]:
        if self.requeued:
            return self.requeued[0]
        return self.heap[0][1] if self.heap else None

    def pop(self) -> _Request:
        req = (self.requeued.popleft() if self.requeued
               else heapq.heappop(self.heap)[1])
        self.rows -= req.rows
        self.depth -= 1
        return req

    def clear(self) -> None:
        self.requeued.clear()
        self.heap = []
        self.rows = 0
        self.depth = 0


class MicroBatcher:
    """Coalescing request queue (see module doc for the policy).

    Parameters
    ----------
    max_batch_rows:
        Payload-row dispatch threshold AND per-request row cap (a
        request must fit one batch whole — results split per request,
        never mid-request).
    max_wait_s:
        Micro-batching window measured from the oldest queued request.
    queue_cap:
        Admission cap in *requests* (the reference point operators
        reason about: one queue slot = one caller waiting).  Under
        tenancy, each tenant's cap is its weight's share of this.
    clock:
        Monotonic-seconds source; injectable for deterministic tests.
    tenant_weights:
        Optional ``{tenant_name: weight}`` traffic-shaping spec
        (module doc).  None = single-queue serving: every request rides
        one implicit default tenant (full cap, full batch share —
        exactly the pre-tenancy behavior).  Tenants not named here
        (including the default tenant for untagged submits) register on
        first use at weight 1.0 — name production tenants explicitly so
        their shares are pinned.
    """

    def __init__(self, max_batch_rows: int, max_wait_s: float,
                 queue_cap: int,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "serve",
                 tenant_weights: Optional[Dict[str, float]] = None):
        expects(max_batch_rows >= 1,
                "MicroBatcher: max_batch_rows=%d", max_batch_rows)
        expects(max_wait_s >= 0.0,
                "MicroBatcher: max_wait_s=%r", max_wait_s)
        expects(queue_cap >= 1, "MicroBatcher: queue_cap=%d", queue_cap)
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_s)
        self.queue_cap = int(queue_cap)
        self.name = str(name)
        self._clock = clock
        self._cond = threading.Condition()
        self._tenants: Dict[str, _TenantQueue] = {}
        if tenant_weights:
            for t, w in tenant_weights.items():
                expects(float(w) > 0.0,
                        "MicroBatcher: tenant %r weight %r must be > 0",
                        t, w)
                self._tenants[str(t)] = _TenantQueue(float(w))
        # arrival-order view across tenants (lazy-swept on pop): the
        # batching window is measured from the OLDEST queued request,
        # which EDF heaps cannot answer
        self._arrivals: "collections.deque[_Request]" = collections.deque()
        self._seq = 0
        self._depth = 0
        self._rows_queued = 0
        # EWMA of observed batch service time (worker feeds it via
        # note_batch_seconds) — the retry_after_s drain estimate's rate
        self._batch_s_ewma = 0.0
        self._paused = False
        self._draining = False
        self._stopped = False

    # ------------------------------------------------------------------ #
    # tenant plumbing
    # ------------------------------------------------------------------ #
    def _tenant_locked(self, name: str) -> _TenantQueue:
        tq = self._tenants.get(name)
        if tq is None:
            tq = self._tenants[name] = _TenantQueue(1.0)
        return tq

    def _tenant_cap_locked(self, name: str) -> int:
        tq = self._tenants.get(name)
        w = tq.weight if tq is not None else 1.0
        total = sum(t.weight for t in self._tenants.values())
        if tq is None:
            total += w
        return max(1, int(self.queue_cap * w / total))

    def tenant_cap(self, tenant: str) -> int:
        """The admission cap ``tenant`` currently gets: its weight's
        share of ``queue_cap`` (the full cap when it is alone)."""
        with self._cond:
            return self._tenant_cap_locked(str(tenant))

    def tenant_depths(self) -> Dict[str, int]:
        """Queued request count per registered tenant."""
        with self._cond:
            return {name: tq.depth
                    for name, tq in self._tenants.items()}

    def tenants(self) -> Dict[str, float]:
        """Registered tenant weights (declared + auto-registered)."""
        with self._cond:
            return {name: tq.weight
                    for name, tq in self._tenants.items()}

    # ------------------------------------------------------------------ #
    # submitter side
    # ------------------------------------------------------------------ #
    def _retry_after_locked(self) -> float:
        """Estimated queue-drain seconds — the
        ``ServiceOverloadError.retry_after_s`` hint: batches left to
        drain × the observed batch service time (the coalesce window
        when no batch has been timed yet)."""
        batches = max(1, -(-self._rows_queued // self.max_batch_rows))
        per = (self._batch_s_ewma if self._batch_s_ewma > 0.0
               else max(self.max_wait_s, 1e-3))
        return batches * per

    def note_batch_seconds(self, seconds: float) -> None:
        """Feed one observed batch service time into the drain-estimate
        EWMA (the worker calls this per finished batch)."""
        with self._cond:
            if self._batch_s_ewma <= 0.0:
                self._batch_s_ewma = float(seconds)
            else:
                self._batch_s_ewma = (0.7 * self._batch_s_ewma
                                      + 0.3 * float(seconds))

    def submit(self, payload, rows: int,
               deadline_t: Optional[float] = None,
               tenant: Optional[str] = None,
               tier: int = 0) -> ServeFuture:
        """Enqueue one request; returns its future.

        ``tenant`` tags the request for weighted-fair shaping (None =
        the default tenant); ``tier`` is the priority override (lower =
        more urgent) applied before EDF within the tenant's share.

        Raises :class:`ServiceOverloadError` — naming the tenant and
        carrying a ``retry_after_s`` drain estimate — at the tenant's
        (or the global) admission cap, and :class:`LogicError` once
        draining/stopped (a closed service must fail loudly, not buffer
        into a queue nobody serves).
        """
        expects(1 <= rows <= self.max_batch_rows,
                "submit: %d rows outside [1, max_batch_rows=%d] — a "
                "request must fit one batch whole", rows,
                self.max_batch_rows)
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        req = _Request(payload, rows, self._clock(), deadline_t,
                       self.name, tenant, int(tier))
        with self._cond:
            if self._draining or self._stopped:
                raise LogicError(
                    "submit: service is draining/closed and no longer "
                    "accepts requests")
            tq = self._tenant_locked(tenant)
            cap = self._tenant_cap_locked(tenant)
            if tq.depth >= cap:
                raise ServiceOverloadError(
                    "serve queue over tenant %r's admission share; "
                    "shed and retry with backoff" % tenant,
                    tq.depth, cap, tenant=tenant,
                    retry_after_s=self._retry_after_locked())
            if self._depth >= self.queue_cap:
                raise ServiceOverloadError(
                    "serve queue over admission cap; shed and retry "
                    "with backoff", self._depth, self.queue_cap,
                    tenant=tenant,
                    retry_after_s=self._retry_after_locked())
            req.seq = self._seq
            self._seq += 1
            # the admitted event is recorded BEFORE the request becomes
            # visible to the worker (push/notify below): once pushed,
            # an idle worker can form the batch and append
            # batch_formed/resolved to this trace immediately — the
            # timeline must already start with `admitted` or the
            # queue-wait bracket renders out of order.  DRR share
            # context is captured under the same lock the admission
            # decision used (docs/OBSERVABILITY.md); the recorder lock
            # is a leaf and nests safely under the cond lock.
            flight.record(
                "admitted", service=self.name, trace=req.trace,
                rows=rows, tier=int(tier),
                deadline_in_s=(None if deadline_t is None else
                               round(deadline_t - req.enqueue_t, 6)),
                depth=self._depth + 1, tenant_depth=tq.depth + 1,
                tenant_weight=tq.weight, cap=cap)
            tq.push(req)
            self._arrivals.append(req)
            self._depth += 1
            self._rows_queued += req.rows
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def rows_queued(self) -> int:
        with self._cond:
            return self._rows_queued

    def empty(self) -> bool:
        with self._cond:
            return self._depth == 0

    def draining(self) -> bool:
        """Whether admission has stopped (drain/close in progress) —
        maintenance work (e.g. compaction) should not start once the
        service is winding down."""
        with self._cond:
            return self._draining

    def paused(self) -> bool:
        """Whether batch formation is paused (recovery in progress)."""
        with self._cond:
            return self._paused

    # ------------------------------------------------------------------ #
    # recovery seams (raft_tpu/serve/resilience.py)
    # ------------------------------------------------------------------ #
    def pause(self) -> None:
        """Stop forming batches (recovery in progress): queued requests
        stay queued, the worker idles.  Unlike :meth:`begin_drain` this
        is reversible (:meth:`resume`); the service façade sheds *new*
        submits with ``ServiceUnavailableError`` while paused."""
        with self._cond:
            self._paused = True
            self._cond.notify_all()

    def resume(self) -> None:
        """Re-admit after a pause: batch formation restarts and the
        queued backlog (including recovery re-enqueues) dispatches."""
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def requeue(self, reqs: List[_Request]) -> bool:
        """Put already-admitted requests back at the FRONT of their
        tenants' queues (recovery re-enqueue: riders of a batch that
        died while the breaker tripped are served after recovery
        instead of lost).  Bypasses the admission cap and the drain
        gate — these requests were admitted once and must resolve
        exactly once.  Returns False (caller must fail the futures
        instead) once the queue is stopped: after :meth:`shutdown`
        nobody will ever serve them."""
        with self._cond:
            if self._stopped:
                return False
            for req in reversed(reqs):
                req.taken = False
                self._tenant_locked(req.tenant).push_front(req)
                self._arrivals.appendleft(req)
                self._depth += 1
                self._rows_queued += req.rows
            self._cond.notify_all()
        return True

    # ------------------------------------------------------------------ #
    # worker side
    # ------------------------------------------------------------------ #
    def _oldest_locked(self) -> Optional[_Request]:
        while self._arrivals and self._arrivals[0].taken:
            self._arrivals.popleft()
        return self._arrivals[0] if self._arrivals else None

    def _pop_from_locked(self, tq: _TenantQueue) -> _Request:
        req = tq.pop()
        req.taken = True
        self._depth -= 1
        self._rows_queued -= req.rows
        return req

    def _pop_batch_locked(self) -> List[_Request]:
        """Form one batch by deficit round robin across tenants with
        queued work, EDF within each tenant (module doc).

        Each active tenant's per-window quantum is its weight's share
        of ``max_batch_rows`` **over the tenants that currently have
        work** — an idle tenant's share is redistributed by
        construction.  The quantum adds to a per-tenant *deficit*
        carried across windows (capped at the window, reset when the
        queue empties), and the tenant pops whole requests while the
        head fits its deficit — so a request bigger than one window's
        share accumulates credit instead of starving, and a backlogged
        bulk tenant's service rate is *bounded by its weight share per
        window*.  Deliberately NOT work-conserving against an active
        tenant's backlog: backfilling the window from an over-quota
        tenant would inflate every batch's execution time and convert
        the bulk backlog into latency for the interactive class — the
        quota (docs/SERVING.md "Traffic shaping") is exactly the bound
        that keeps interactive p99 near its solo value while bulk
        saturates.  A round that pops nothing (every head larger than
        its tenant's deficit) grants another quantum and retries —
        liveness over strictness; deficits cap at the window so this
        terminates."""
        active = [tq for tq in self._tenants.values() if tq.depth]
        if not active:
            return []
        batch: List[_Request] = []
        remaining = self.max_batch_rows
        total_w = sum(tq.weight for tq in active)
        while True:
            for tq in active:
                tq.deficit = min(
                    float(self.max_batch_rows),
                    tq.deficit
                    + self.max_batch_rows * tq.weight / total_w)
                while remaining > 0:
                    head = tq.peek()
                    if (head is None or head.rows > tq.deficit
                            or head.rows > remaining):
                        break
                    req = self._pop_from_locked(tq)
                    batch.append(req)
                    tq.deficit -= req.rows
                    remaining -= req.rows
                if not tq.depth:
                    # DRR reset: an emptied queue banks no credit
                    tq.deficit = 0.0
            if batch or remaining <= 0:
                return batch
            # nothing popped: every active head is larger than its
            # tenant's deficit — grant another quantum rather than
            # returning an empty "ready" batch (deficits cap at the
            # full window, and every request fits a window, so at
            # most a few rounds run)
            if all(tq.deficit >= self.max_batch_rows
                   for tq in active):
                # capped deficits and still nothing fits ``remaining``
                # — cannot happen for a fresh batch, but guard the
                # loop anyway
                return batch

    def _ready_locked(self, now: float) -> bool:
        if not self._depth:
            return False
        if self._draining or self._stopped:
            return True
        if self._paused:
            return False
        if self._rows_queued >= self.max_batch_rows:
            return True
        head = self._oldest_locked()
        return (head is not None
                and (now - head.enqueue_t) >= self.max_wait_s)

    def take(self) -> Optional[List[_Request]]:
        """Non-blocking: a batch if the policy says dispatch now, else
        None.  The deterministic-test entry point (fake clock + manual
        worker stepping); also used by drain's inline fallback."""
        with self._cond:
            if not self._ready_locked(self._clock()):
                return None
            return self._pop_batch_locked()

    def wait_for_batch(self, timeout: Optional[float] = None
                       ) -> Optional[List[_Request]]:
        """Blocking: the next batch, or None once stopped and empty
        (the worker loop's exit signal).

        ``timeout`` bounds the wait: an empty list is returned when it
        elapses with no batch ready — the worker loop's maintenance
        poll (periodic compaction must get the thread even while the
        queue idles; ``[]`` is "no work yet", distinct from the None
        exit signal)."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                if self._ready_locked(self._clock()):
                    return self._pop_batch_locked()
                if self._stopped and not self._depth:
                    return None
                poll = None
                if deadline is not None:
                    poll = deadline - self._clock()
                    if poll <= 0:
                        return []
                head = self._oldest_locked()
                if head is not None and not self._paused:
                    remaining = max(1e-3,
                                    head.enqueue_t + self.max_wait_s
                                    - self._clock())
                    self._cond.wait(timeout=remaining if poll is None
                                    else min(remaining, poll))
                else:
                    # empty — or paused for recovery: an overdue head
                    # request must not turn this into a 1 kHz spin;
                    # resume() notifies, so the wake-up is immediate
                    self._cond.wait(timeout=poll)

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def begin_drain(self) -> None:
        """Stop admitting; flush queued requests immediately (no point
        holding the micro-batch window open — nobody new is coming).
        Overrides a recovery pause: drain must serve (or fail) the
        queue out, never hold it hostage to a recovery that will not
        finish."""
        with self._cond:
            self._draining = True
            self._paused = False
            self._cond.notify_all()

    def shutdown(self) -> List[_Request]:
        """Stop the queue for good; returns any requests still queued
        (a non-draining close must fail them, never strand their
        futures).  After shutdown ``wait_for_batch`` returns None."""
        with self._cond:
            self._draining = True
            self._stopped = True
            # dedup by identity: a requeued request re-enters
            # _arrivals at the front while its popped-then-requeued
            # stale entry may still sit mid-deque (the lazy sweep only
            # trims the head) — listing it twice would fail its future
            # twice and over-count the expiry counter
            seen: set = set()
            leftovers = []
            for r in self._arrivals:
                if not r.taken and id(r) not in seen:
                    seen.add(id(r))
                    leftovers.append(r)
            self._arrivals.clear()
            for tq in self._tenants.values():
                tq.clear()
            self._depth = 0
            self._rows_queued = 0
            self._cond.notify_all()
        return leftovers
