"""Precompiled specializations: persistent compile cache + AOT warmup.

Reference: cpp/src/ pre-instantiates the hot templates into
``libraft_distance.so`` / ``libraft_nn.so`` (cpp/CMakeLists.txt:122-156) so
consumers skip template compilation.  The XLA analog has two layers:

- a **persistent compilation cache**: every jit executable is serialized to
  disk keyed by (HLO, flags, platform), so any process on the machine skips
  recompilation of previously-seen programs (the .so role, but automatic
  and covering every shape actually used);
- **AOT warmup**: ``jax.jit(...).lower(...).compile()`` for the known-hot
  configurations (README-example pairwise shapes, fused kNN tiles), run
  once at deploy time to populate the cache before first use.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "raft_tpu", "xla_cache")

_enabled_dir: Optional[str] = None


def enable_persistent_cache(path: Optional[str] = None,
                            min_compile_secs: float = 0.0) -> str:
    """Turn on the on-disk executable cache (idempotent).

    Returns the cache directory.  Safe to call before or after other jax
    use; programs compiled afterwards are cached.  This is the SINGLE
    owner of the cache config (the bench and the measurement tools call
    through here) — note this environment's JAX does not read
    JAX_COMPILATION_CACHE_DIR from the env, so the explicit config
    update is what actually enables caching.  ``min_compile_secs``:
    0.0 caches every program (the AOT-warmup default); the bench passes
    5.0 so only real accelerator compiles are worth disk.
    """
    global _enabled_dir
    path = path or _DEFAULT_CACHE
    if _enabled_dir == path:
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled_dir = path
    return path


def aot_compile(fn, *example_args):
    """Ahead-of-time lower + compile ``fn`` for the example arguments'
    shapes/dtypes; returns the compiled executable (callable).  Static
    configuration (k, metric, …) should be closed over in ``fn``."""
    shaped = [jax.ShapeDtypeStruct(jnp.shape(a), jnp.asarray(a).dtype)
              if not isinstance(a, jax.ShapeDtypeStruct) else a
              for a in example_args]
    return jax.jit(fn).lower(*shaped).compile()


# --------------------------------------------------------------------- #
# hot-config registry (the role of cpp/src/*/specializations lists)
# --------------------------------------------------------------------- #
def default_specializations() -> Dict[str, Tuple[Any, Tuple]]:
    """Name → (fn, example_args) for the configurations worth prebuilding:
    the README pairwise example, the bench pairwise shape, and the fused
    kNN step (reference cpp/src/distance/specializations + cpp/src/nn)."""
    from raft_tpu.distance import DistanceType, pairwise_distance
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    f32 = jnp.float32
    specs: Dict[str, Tuple[Any, Tuple]] = {}

    def pw(metric):
        return lambda x, y: pairwise_distance(x, y, metric)

    readme = (jax.ShapeDtypeStruct((1024, 64), f32),
              jax.ShapeDtypeStruct((1024, 64), f32))
    bench = (jax.ShapeDtypeStruct((8192, 128), f32),
             jax.ShapeDtypeStruct((8192, 128), f32))
    specs["pairwise_l2sqrt_1k_64"] = (pw(DistanceType.L2SqrtExpanded), readme)
    specs["pairwise_l2_8k_128"] = (pw(DistanceType.L2Expanded), bench)
    specs["pairwise_cosine_8k_128"] = (pw(DistanceType.CosineExpanded), bench)
    specs["pairwise_l1_1k_64"] = (pw(DistanceType.L1), readme)

    knn_fn = lambda ix, q: fused_l2_knn(ix, q, 100)
    specs["fused_l2_knn_100"] = (
        knn_fn, (jax.ShapeDtypeStruct((65536, 128), f32),
                 jax.ShapeDtypeStruct((1024, 128), f32)))

    return specs


def warmup(names: Optional[Sequence[str]] = None,
           cache_dir: Optional[str] = None) -> Dict[str, Any]:
    """Compile the named specializations (all by default) into the
    persistent cache; returns name → compiled executable."""
    enable_persistent_cache(cache_dir)
    registry = default_specializations()
    out = {}
    for name in (names or registry.keys()):
        fn, args = registry[name]
        out[name] = aot_compile(fn, *args)
    return out
