"""Scoped profiler: nested timed spans + instrumented-jit attribution.

The tracing module (:mod:`raft_tpu.core.tracing`) puts names on the XLA
profiler timeline; this module keeps the *numbers* in-process:

- **Spans** (:meth:`Profiler.span`): nested wall-clock scopes kept as a
  call tree (per-thread nesting, merged across threads by path) and
  mirrored into registry timers so snapshots carry per-primitive
  latency histograms.  Spans also enter :func:`tracing.annotate`, so
  profiler scopes and XLA trace ranges share one name space.
- **profiled** decorator: one-line primitive instrumentation — wraps a
  function in a span and a ``raft_tpu_<layer>_<name>_seconds`` timer.
  NOTE on async dispatch: JAX returns before the device finishes, so a
  primitive's timer measures host-side dispatch (trace + enqueue)
  unless the caller syncs inside the span; bench code that wants
  device-complete numbers blocks via ``handle.sync_stream()`` or
  ``block_until_ready`` as it always has.
- **profiled_jit**: the instrumented ``jax.jit`` entry point.  It keys
  an explicit executable cache on (fn, input avals, static args) and
  separates *compile* from *execute*: a cache miss lowers and compiles
  ahead-of-time, timing just the compile
  (``raft_tpu_jit_compile_seconds{fn=...}``), then every call runs the
  cached executable inside the fn's span.  Hits/misses are counted per
  fn (``raft_tpu_jit_cache_{hits,misses}_total``) and per (fn, shape)
  key (:func:`compile_cache_stats`), which is how the bench tells
  steady-state throughput from retrace regressions.

The default profiler reports into :func:`metrics.default_registry`; a
``Handle`` carries a profiler reference (``handle.profiler``) so
primitives threaded through a handle reach the same instance the
session snapshots.
"""

from __future__ import annotations

import functools
import inspect
import threading
import time
import warnings
from typing import Dict, Optional, Tuple

from raft_tpu.core import inventory as _inventory
from raft_tpu.core import metrics as _metrics
from raft_tpu.core import tracing

__all__ = ["Profiler", "default_profiler", "profiled", "profiled_jit",
           "compile_cache_stats", "reset_compile_cache_stats",
           "last_jit_fn"]


class _SpanNode:
    __slots__ = ("name", "count", "total_s", "children")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_s = 0.0
        self.children: Dict[str, "_SpanNode"] = {}


class _SpanScope:
    """One span activation (each ``with`` gets its own scope object, so
    the same span name is re-entrant and thread-safe)."""

    def __init__(self, prof: "Profiler", name: str, timer):
        self._prof = prof
        self._name = name
        self._timer = timer
        self._ann = None

    def __enter__(self):
        self._prev_active = getattr(_tls_active, "prof", None)
        _tls_active.prof = self._prof
        self._prof._path_stack().append(self._name)
        self._ann = tracing.annotate(self._name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        self._ann.__exit__(exc_type, exc, tb)
        stack = self._prof._path_stack()
        path = tuple(stack)
        stack.pop()
        _tls_active.prof = self._prev_active
        self._prof._record(path, dt)
        if self._timer is not None:
            self._timer.observe(dt)


class _NullScope:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


_NULL = _NullScope()

# innermost profiler with an open span on this thread: inner
# instrumentation that has no handle in reach (profiled_jit's
# "jit.<fn>" spans) attributes to its caller's profiler, so a
# handle-scoped profiler's tree keeps its compile/execute children
_tls_active = threading.local()

# last profiled_jit executable run on each thread — the serve
# scheduler's attribution key for the device-complete roofline
# bracket (``raft_tpu_serve_device_seconds{fn=...}``): the scheduler
# can't name the program behind its opaque ``execute`` closure, but
# the wrapper that just ran on its batch thread can
_tls_last_jit = threading.local()


def last_jit_fn() -> Optional[str]:
    """Name of the most recent :func:`profiled_jit` executable run on
    THIS thread (None if none ran since :func:`_clear_last_jit_fn`).
    Matches the cost inventory's per-fn key, so callers can join
    wall-clock brackets against ``inventory.summary()["per_fn"]``."""
    return getattr(_tls_last_jit, "fn", None)


def _clear_last_jit_fn() -> None:
    _tls_last_jit.fn = None


def _current_profiler() -> "Profiler":
    return getattr(_tls_active, "prof", None) or _default_profiler


class Profiler:
    """Aggregating span profiler.

    Nesting is tracked per thread (a watchdog thread's spans do not
    graft onto the main thread's open scope); the aggregate tree merges
    all threads by span path, so ``report()`` is one tree regardless of
    who timed what.
    """

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        self._registry = registry
        self._lock = threading.Lock()
        self._root = _SpanNode("")
        self._tls = threading.local()
        # resolved span timers, invalidated by registry generation:
        # spans wrap every instrumented primitive, so the name
        # validation + family lookup must not run per call
        self._timer_cache = {}

    @property
    def registry(self) -> _metrics.MetricsRegistry:
        return (self._registry if self._registry is not None
                else _metrics.default_registry())

    def _path_stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _record(self, path: Tuple[str, ...], dt: float) -> None:
        with self._lock:
            node = self._root
            for name in path:
                nxt = node.children.get(name)
                if nxt is None:
                    nxt = node.children[name] = _SpanNode(name)
                node = nxt
            node.count += 1
            node.total_s += dt

    # ------------------------------------------------------------------ #
    def span(self, name: str, layer: Optional[str] = None):
        """Open a nested timed scope.  When ``layer`` is given, the
        span additionally feeds a
        ``raft_tpu_<layer>_<name>_seconds`` registry timer (a leading
        ``"<layer>."`` on the span name is not repeated in the metric;
        remaining dots become underscores)."""
        if not _metrics.is_enabled():
            return _NULL
        timer = None
        if layer is not None:
            reg = self.registry
            gen = reg.generation
            cached = self._timer_cache.get((name, layer))
            if cached is not None and cached[0] == gen:
                timer = cached[1]
            else:
                mname = (name[len(layer) + 1:]
                         if name.startswith(layer + ".") else name)
                timer = reg.timer(
                    _metrics.metric_name(
                        layer, mname.replace(".", "_") + "_seconds"),
                    help="span '%s' duration (host-side dispatch)" % name)
                self._timer_cache[(name, layer)] = (gen, timer)
        return _SpanScope(self, name, timer)

    def reset(self) -> None:
        with self._lock:
            self._root = _SpanNode("")

    def tree(self) -> Dict:
        """The span tree as plain dicts (for JSON artifacts)."""

        def conv(node: _SpanNode) -> Dict:
            out = {"count": node.count, "total_s": node.total_s}
            if node.children:
                out["children"] = {n: conv(c)
                                   for n, c in sorted(node.children.items())}
            return out

        with self._lock:
            return {n: conv(c)
                    for n, c in sorted(self._root.children.items())}

    def report(self) -> str:
        """Human-readable span tree: count, total, mean per scope, with
        children indented under their parent."""
        lines = ["profiler report (wall seconds, host-side dispatch "
                 "unless the span syncs)"]

        def walk(node: _SpanNode, depth: int) -> None:
            mean = node.total_s / node.count if node.count else 0.0
            lines.append("%s%-*s  n=%-6d total=%.6fs  mean=%.6fs"
                         % ("  " * depth, max(1, 40 - 2 * depth),
                            node.name, node.count, node.total_s, mean))
            for _, child in sorted(node.children.items()):
                walk(child, depth + 1)

        with self._lock:
            top = sorted(self._root.children.items())
        if not top:
            lines.append("  (no spans recorded)")
        for _, child in top:
            walk(child, 1)
        return "\n".join(lines)


_default_profiler = Profiler()


def default_profiler() -> Profiler:
    """The process-wide profiler (shared registry with the metrics
    default; what ``Handle.profiler`` points at unless overridden)."""
    return _default_profiler


def profiled(layer: str, name: Optional[str] = None):
    """Decorator: run the function inside a ``<layer>.<name>`` span
    feeding ``raft_tpu_<layer>_<name>_seconds``.  The span name is the
    function name unless given.  A ``handle=`` keyword carrying a
    scoped profiler routes the span there (same contract as
    ``takes_handle``); otherwise the process default is used."""

    def deco(fn):
        span_name = "%s.%s" % (layer, name or fn.__name__)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            prof = (getattr(kwargs.get("handle"), "profiler", None)
                    or _current_profiler())
            with prof.span(span_name, layer=layer):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------- #
# instrumented jit
# ---------------------------------------------------------------------- #
_jit_lock = threading.Lock()
# (fn_name, key) -> {"hits": int, "misses": int, "compile_s": float}
_jit_stats: Dict[Tuple[str, Tuple], Dict[str, float]] = {}


_DONATION_WARNING_MSG = ".*donated buffers were not usable.*"


def _ensure_donation_warning_filter():
    """Silence XLA's "donated buffers were not usable" compile warning.
    Donation in this repo is always DELIBERATE best-effort buffer
    recycling — when a program's output geometry cannot alias the
    donated input, XLA simply keeps a copy, which is the documented
    acceptable outcome (docs/ZERO_COPY.md), not a caller bug worth a
    per-compile warning.  A module-level filter rather than a
    per-compile ``warnings.catch_warnings()`` block: that context
    mutates process-global filter state non-thread-safely, and compiles
    now happen on serve worker threads.  Re-checked before every
    donating compile (not installed once): pytest and any user
    ``catch_warnings`` block restore ``warnings.filters`` wholesale,
    silently discarding an entry installed earlier — scanning for the
    filter and re-adding it when missing survives those resets, and an
    idempotent scan never grows the filter list."""
    with _jit_lock:
        for f in warnings.filters:
            if (f[0] == "ignore" and f[1] is not None
                    and f[1].pattern == _DONATION_WARNING_MSG):
                return
        warnings.filterwarnings("ignore", message=_DONATION_WARNING_MSG)


def _static_key(v):
    """Statics key by the object itself (jax.jit's contract: statics
    are hashable and compared by __eq__) — the object living inside the
    cache key keeps it alive, so an id()-based repr can never alias a
    recycled address onto a stale executable.  Unhashable values fall
    back to repr (plain jax.jit would reject them outright)."""
    try:
        hash(v)
    except TypeError:
        return ("__unhashable_repr__", repr(v))
    return v


def _leaf_key(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        # sharding is part of the executable's calling convention: an
        # AOT-compiled program replayed for same-shape arrays on a
        # *different device* raises instead of recompiling, so the key
        # must distinguish placements the way jax.jit's own cache does
        # (numpy/host inputs have no sharding and key as None)
        sharding = getattr(x, "sharding", None)
        return (tuple(x.shape), str(x.dtype),
                None if sharding is None else str(sharding))
    # dynamic Python scalars key like jax.jit's avals (type, not value):
    # keying on the value would report a fresh compile-cache miss — and
    # compile a fresh executable — for every distinct tol/seed/... even
    # though the lowered program takes the scalar as a runtime argument
    if isinstance(x, (bool, int, float, complex)):
        return ("scalar", type(x).__name__)
    return ("py", repr(x))


def compile_cache_stats() -> Dict[str, Dict[str, Dict[str, float]]]:
    """Per-(fn, shape-key) compile-cache accounting:
    ``{fn_name: {key_repr: {hits, misses, compile_s}}}``."""
    with _jit_lock:
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        for (fn_name, key), st in _jit_stats.items():
            out.setdefault(fn_name, {})[repr(key)] = dict(st)
        return out


def reset_compile_cache_stats() -> None:
    """Zero the per-(fn, shape) accounting (test isolation).  Compiled
    executables stay cached on their wrappers — this resets the
    *statistics*, matching what tests and stats windows need; the next
    call at a known shape counts as a hit again."""
    with _jit_lock:
        _jit_stats.clear()


def profiled_jit(fn=None, *, name: Optional[str] = None,
                 static_argnames: Tuple[str, ...] = (),
                 donate_argnames: Tuple[str, ...] = ()):
    """``jax.jit`` with compile-cache observability.

    Keys an explicit executable cache on (function, input avals, static
    arguments).  A **miss** lowers + compiles ahead-of-time and records
    the compile seconds and a miss count; a **hit** runs the cached
    executable directly and records a hit.  Execution always runs in a
    ``jit.<name>`` span.  Metrics (all labeled ``fn=<name>``):

    - ``raft_tpu_jit_cache_misses_total`` / ``raft_tpu_jit_cache_hits_total``
    - ``raft_tpu_jit_compile_seconds`` (timer)

    Static arguments may be passed positionally or by keyword — the
    wrapper normalizes through the signature.  If ahead-of-time
    lowering fails for a key (an argument kind AOT cannot express), the
    wrapper falls back to the plain jitted call for that key and
    attributes that first call's full time to compile — degraded
    attribution, never a behavior change.  Functions with ``*args`` /
    ``**kwargs`` are not AOT-split; they get hit/miss counting with the
    lazy path only.

    ``donate_argnames`` passes through to ``jax.jit`` (preserved by the
    AOT lower/compile path): the named arrays are CONSUMED by the call
    — XLA may recycle their buffers for outputs and the caller's
    reference is deleted.  The zero-copy donation contract (which
    raft_tpu entry points consume which arrays) is documented in
    docs/ZERO_COPY.md.
    """
    if fn is None:
        return functools.partial(profiled_jit, name=name,
                                 static_argnames=static_argnames,
                                 donate_argnames=donate_argnames)

    import jax

    fn_name = name or getattr(fn, "__name__", "jit_fn")
    statics = tuple(static_argnames)
    jit_kw = {}
    if statics:
        jit_kw["static_argnames"] = statics
    if donate_argnames:
        jit_kw["donate_argnames"] = tuple(donate_argnames)
    jitted = jax.jit(fn, **jit_kw)
    sig = inspect.signature(fn)
    # *args/**kwargs/positional-only signatures can't be normalized to
    # by-name calls; they get hit/miss counting on the lazy path only
    has_varargs = any(
        p.kind in (inspect.Parameter.VAR_POSITIONAL,
                   inspect.Parameter.VAR_KEYWORD,
                   inspect.Parameter.POSITIONAL_ONLY)
        for p in sig.parameters.values())
    # per-wrapper executable cache: key -> ("aot", compiled) | ("lazy",)
    execs: Dict[Tuple, Tuple] = {}

    def _metric(kind, mname, **kw):
        return getattr(_metrics.default_registry(), kind)(
            mname, labels=("fn",), **kw).labels(fn=fn_name)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        # two transparent bypasses, both routed through the plain jit
        # (exactly what an uninstrumented jax.jit would do):
        # - jax.disable_jit(): the AOT Compiled object refuses to run
        #   eagerly, while jitted() honors the flag for step/print
        #   debugging;
        # - called under an outer trace (arguments are Tracers): the
        #   AOT executable can't take tracers and "cache hit" is
        #   meaningless at trace time.
        if (getattr(jax.config, "jax_disable_jit", False)
                or any(isinstance(x, jax.core.Tracer)
                       for x in jax.tree_util.tree_leaves((args, kwargs)))):
            return jitted(*args, **kwargs)
        if has_varargs:
            static_kw = dyn_kw = None
            key_src = (args, kwargs)
        else:
            # normalize to by-name calls: statics may be interleaved
            # positionally (e.g. f(X, k, tol) with static k), so a
            # positional re-call would misalign the dynamic args
            bound = sig.bind(*args, **kwargs)
            # defaults participate in the key: f(x) and f(x, k=default)
            # are the same program and must share one executable
            bound.apply_defaults()
            static_kw = {k: v for k, v in bound.arguments.items()
                         if k in statics}
            dyn_kw = {k: v for k, v in bound.arguments.items()
                      if k not in statics}
            key_src = dyn_kw
        leaves, treedef = jax.tree_util.tree_flatten(key_src)
        key = (treedef, tuple(_leaf_key(x) for x in leaves),
               None if static_kw is None else
               tuple(sorted(((k, _static_key(v))
                             for k, v in static_kw.items()),
                            key=lambda kv: kv[0])))
        with _jit_lock:
            entry = execs.get(key)
            st = _jit_stats.setdefault(
                (fn_name, key), {"hits": 0, "misses": 0, "compile_s": 0.0})
        if entry is None:
            if donate_argnames:
                # the warning only fires at compile time, so the miss
                # path is the one place the filter must be live
                _ensure_donation_warning_filter()
            _metric("counter", "raft_tpu_jit_cache_misses_total",
                    help="instrumented-jit compile-cache misses").inc()
            t0 = time.perf_counter()
            entry = ("lazy",)
            if not has_varargs:
                try:
                    compiled = jitted.lower(
                        **static_kw, **dyn_kw).compile()
                    entry = ("aot", compiled)
                    # cost inventory (docs/OBSERVABILITY.md "Ops
                    # plane"): the executable is interrogated ONCE,
                    # here, where it is born — never on the hit path
                    _inventory.note_compiled(fn_name, key, compiled)
                except Exception:
                    pass
            out = None
            if entry[0] == "lazy":
                # no AOT split for this key: run the (compiling) first
                # call once and attribute its full time to compile
                _tls_last_jit.fn = fn_name
                with _current_profiler().span("jit.%s" % fn_name,
                                              layer="jit"):
                    out = (jitted(*args, **kwargs) if has_varargs
                           else jitted(**static_kw, **dyn_kw))
            dt = time.perf_counter() - t0
            _metric("timer", "raft_tpu_jit_compile_seconds",
                    help="instrumented-jit compile time").observe(dt)
            with _jit_lock:
                execs[key] = entry
                st["misses"] += 1
                st["compile_s"] += dt
            if entry[0] == "lazy":
                return out
        else:
            _metric("counter", "raft_tpu_jit_cache_hits_total",
                    help="instrumented-jit compile-cache hits").inc()
            with _jit_lock:
                st["hits"] += 1
        _tls_last_jit.fn = fn_name
        with _current_profiler().span("jit.%s" % fn_name, layer="jit"):
            if entry[0] == "aot":
                return entry[1](**dyn_kw)
            if has_varargs:
                return jitted(*args, **kwargs)
            return jitted(**static_kw, **dyn_kw)

    wrapper.__wrapped_jit__ = jitted
    return wrapper
