"""Typed metrics registry: counters, gauges, timer-histograms.

The reference's only runtime observability is NVTX ranges
(cpp/include/raft/common/nvtx.hpp) — numbers live in external profilers.
This module is the in-process half the TPU build needs for
measurement-driven work (the CUDA-L2 / HiCCL methodology both start from
per-primitive timing and per-collective byte accounting): a small,
thread-safe, dependency-free registry whose snapshots travel with bench
artifacts.

Metric model (a deliberately tiny subset of the Prometheus data model):

- ``Counter``  — monotonically increasing float/int.
- ``Gauge``    — settable value; tracks the max it has ever held
  (``high_water``) so peak accounting needs no second metric.
- ``Timer``    — duration histogram: exact count/total/min/max plus a
  bounded reservoir of recent samples for p50/p95 quantiles.

Every metric is a *family* that may carry labels
(``registry.counter("raft_tpu_comms_bytes_total", labels=("verb",))``;
``fam.labels(verb="allreduce").inc(n)``).  Families declared without
label names act directly as their single unlabeled series.

Naming scheme: ``raft_tpu_<layer>_<name>`` (see docs/OBSERVABILITY.md);
:func:`metric_name` builds and validates it.

Export: :meth:`MetricsRegistry.snapshot` (plain dicts, isolated from
later mutation), :meth:`~MetricsRegistry.to_json`, and
:meth:`~MetricsRegistry.to_prometheus` (text exposition format;
:func:`parse_prometheus` reads it back for round-trip tests and for
scraping bench artifacts).

The ``RAFT_TPU_METRICS`` environment variable ("0" disables) or
:func:`set_enabled` turn recording into a no-op globally — the registry
and its metric objects stay usable so instrumented code never branches.
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import threading
import time
from typing import Dict, Iterator, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Timer", "MetricsRegistry",
    "default_registry", "metric_name", "parse_prometheus",
    "set_enabled", "is_enabled",
]

_enabled = os.environ.get("RAFT_TPU_METRICS", "1") != "0"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# bounded reservoir: quantiles reflect the most recent window, while
# count/total/min/max stay exact over the metric's whole lifetime
TIMER_RESERVOIR = 2048


def set_enabled(on: bool) -> None:
    """Globally enable/disable metric recording (RAFT_TPU_METRICS=0)."""
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


def metric_name(layer: str, name: str) -> str:
    """Canonical ``raft_tpu_<layer>_<name>`` metric name."""
    full = "raft_tpu_%s_%s" % (layer, name)
    if not _NAME_RE.match(full):
        raise ValueError("invalid metric name %r" % full)
    return full


class _Series:
    """One labeled child of a metric family; subclasses add semantics."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock


class Counter(_Series):
    """Monotonic counter."""

    def __init__(self, lock):
        super().__init__(lock)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        if n < 0:
            raise ValueError("Counter.inc: negative increment %r" % n)
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _snapshot(self):
        return {"value": self.value}


class Gauge(_Series):
    """Settable value; remembers the highest value it has held."""

    def __init__(self, lock):
        super().__init__(lock)
        self._value = 0.0
        self._high_water = 0.0

    def set(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = v
            if v > self._high_water:
                self._high_water = v

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n
            if self._value > self._high_water:
                self._high_water = self._value

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def _add_raw(self, n: float) -> None:
        """Unconditional adjustment, bypassing the enable gate — for
        *paired* accounting (alloc/free) whose halves must balance even
        if recording is toggled between them."""
        with self._lock:
            self._value += n
            if self._value > self._high_water:
                self._high_water = self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def high_water(self) -> float:
        with self._lock:
            return self._high_water

    def _snapshot(self):
        with self._lock:
            return {"value": self._value, "high_water": self._high_water}


class Timer(_Series):
    """Duration histogram (seconds): exact count/total/min/max, plus a
    bounded reservoir of recent samples for p50/p95."""

    def __init__(self, lock):
        super().__init__(lock)
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._samples = collections.deque(maxlen=TIMER_RESERVOIR)

    def observe(self, seconds: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self.count += 1
            self.total += seconds
            if seconds < self.min:
                self.min = seconds
            if seconds > self.max:
                self.max = seconds
            self._samples.append(seconds)

    def time(self):
        """``with timer.time(): ...`` — observe the block's wall time."""
        return _TimerScope(self)

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the sample reservoir (0 if empty):
        the ceil(q*n)-th smallest sample, so p50 of two samples is the
        *lower* one, not the max."""
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
        idx = max(0, math.ceil(q * len(s)) - 1)
        return s[min(len(s) - 1, idx)]

    def _snapshot(self):
        with self._lock:
            if self.count == 0:
                return {"count": 0, "total": 0.0, "mean": 0.0, "min": 0.0,
                        "max": 0.0, "p50": 0.0, "p95": 0.0}
            snap = {"count": self.count, "total": self.total,
                    "mean": self.total / self.count,
                    "min": self.min, "max": self.max}
            s = sorted(self._samples)
        # one sort shared by both quantiles (snapshots walk every timer
        # series; the reservoir is up to 2048 samples)
        for key, q in (("p50", 0.50), ("p95", 0.95)):
            snap[key] = s[min(len(s) - 1,
                              max(0, math.ceil(q * len(s)) - 1))]
        return snap


class _TimerScope:
    def __init__(self, timer: Timer):
        self._timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.observe(time.perf_counter() - self._t0)


_KINDS = {"counter": Counter, "gauge": Gauge, "timer": Timer}


class _Family:
    """A named metric with optional label dimensions."""

    def __init__(self, kind: str, name: str, help: str,
                 label_names: Tuple[str, ...], lock: threading.RLock):
        self.kind = kind
        self.name = name
        self.help = help
        self.label_names = label_names
        self._lock = lock
        self._children: Dict[Tuple[str, ...], _Series] = {}

    def labels(self, **labels) -> _Series:
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                "%s: labels %r do not match declared %r"
                % (self.name, tuple(sorted(labels)), self.label_names))
        key = tuple(str(labels[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind](self._lock)
                self._children[key] = child
            return child

    def _default(self) -> _Series:
        if self.label_names:
            raise ValueError(
                "%s is labeled %r; use .labels(...)"
                % (self.name, self.label_names))
        return self.labels()

    # unlabeled families act directly as their single series
    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def observe(self, seconds: float) -> None:
        self._default().observe(seconds)

    def time(self):
        return self._default().time()

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)

    @property
    def value(self):
        return self._default().value

    @property
    def high_water(self):
        return self._default().high_water

    def series(self) -> Iterator[Tuple[Dict[str, str], _Series]]:
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            yield dict(zip(self.label_names, key)), child

    def _snapshot(self):
        return {
            "type": self.kind,
            "help": self.help,
            "series": [dict(labels=lbls, **child._snapshot())
                       for lbls, child in self.series()],
        }


class MetricsRegistry:
    """Thread-safe named collection of metric families.

    ``counter``/``gauge``/``timer`` are get-or-create: re-declaring an
    existing name returns the same family (and raises if the kind or
    label names disagree — two call sites silently feeding different
    schemas into one name is the classic metrics bug).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._generation = 0

    @property
    def generation(self) -> int:
        """Bumped by :meth:`reset`.  Callers that cache resolved series
        (hot paths) or schedule paired updates (alloc/free accounting)
        compare generations so a reset invalidates the cache instead of
        corrupting a freshly recreated family."""
        with self._lock:
            return self._generation

    def _get_or_create(self, kind: str, name: str, help: str,
                       labels: Sequence[str]) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError("invalid metric name %r" % name)
        label_names = tuple(labels)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError("invalid label name %r" % ln)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(kind, name, help, label_names, self._lock)
                self._families[name] = fam
            elif fam.kind != kind or fam.label_names != label_names:
                raise ValueError(
                    "metric %s already registered as %s%r, requested %s%r"
                    % (name, fam.kind, fam.label_names, kind, label_names))
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._get_or_create("gauge", name, help, labels)

    def timer(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._get_or_create("timer", name, help, labels)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def family_total(self, name: str) -> float:
        """Sum of a family's series values, 0.0 when the family was
        never materialized — the one spelling of the "total of a
        counter across labels" read (bench.py / tools/loadgen.py /
        tests all share it, so absent-family handling cannot skew)."""
        fam = self.get(name)
        if fam is None:
            return 0.0
        return float(sum(s.value for _, s in fam.series()))

    def reset(self) -> None:
        """Drop every family (test isolation / stats-window rollover).
        Bumps :attr:`generation` so cached series and in-flight paired
        accounting from before the reset are discarded, not misapplied
        to the recreated families."""
        with self._lock:
            self._families.clear()
            self._generation += 1

    def locked(self):
        """The registry's (reentrant) lock, for callers that must make
        a generation check atomic with the update it guards — e.g. the
        buffer accounting's check-then-adjust pair, where a reset
        racing between the two would corrupt the recreated gauge.
        Metric operations may be nested inside (same RLock)."""
        return self._lock

    # ------------------------------------------------------------------ #
    # export
    # ------------------------------------------------------------------ #
    def snapshot(self) -> Dict[str, Dict]:
        """Plain-dict copy of every family; isolated from later updates."""
        with self._lock:
            fams = list(self._families.items())
        return {name: fam._snapshot() for name, fam in sorted(fams)}

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def dump_json(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json(indent=2))
            f.write("\n")

    def to_prometheus(self) -> str:
        """Prometheus text exposition format.

        Timers render as summaries: ``<name>{quantile="..."}``,
        ``<name>_sum``, ``<name>_count``, plus a ``<name>_max`` gauge
        (exact lifetime max, which quantiles over a reservoir can't
        promise).  Gauges additionally expose ``<name>_peak`` — the
        high-water mark, so a scraper sees the same peak the JSON
        snapshot carries without needing a second metric.
        """
        lines = []
        for name, fam in sorted(self.snapshot().items()):
            kind = fam["type"]
            if fam["help"]:
                lines.append("# HELP %s %s" % (name, fam["help"]))
            lines.append("# TYPE %s %s"
                         % (name, "summary" if kind == "timer" else kind))
            for s in fam["series"]:
                lbl = s["labels"]
                if kind == "counter":
                    lines.append("%s %r" % (_fmt(name, lbl), s["value"]))
                elif kind == "gauge":
                    lines.append("%s %r" % (_fmt(name, lbl), s["value"]))
                    lines.append("%s %r" % (_fmt(name + "_peak", lbl),
                                            s["high_water"]))
                else:
                    for q, v in (("0.5", s["p50"]), ("0.95", s["p95"])):
                        lines.append("%s %r" % (
                            _fmt(name, dict(lbl, quantile=q)), v))
                    lines.append("%s %r" % (_fmt(name + "_sum", lbl),
                                            s["total"]))
                    lines.append("%s %d" % (_fmt(name + "_count", lbl),
                                            s["count"]))
                    lines.append("%s %r" % (_fmt(name + "_max", lbl),
                                            s["max"]))
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    body = ",".join('%s="%s"' % (k, _escape(v))
                    for k, v in sorted(labels.items()))
    return "%s{%s}" % (name, body)


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace(
        "\n", r"\n")


# the label body may contain '}' inside quoted values, so it is matched
# as a sequence of quoted strings / non-brace runs, not [^}]*
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<labels>(?:[^{}"]|"(?:[^"\\]|\\.)*")*)\})?\s+(?P<value>\S+)$')
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape(v: str) -> str:
    # single left-to-right pass: sequential str.replace would corrupt a
    # literal backslash followed by 'n' into a newline
    return _UNESCAPE_RE.sub(
        lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)


def parse_prometheus(text: str) -> Dict[str, Dict[Tuple, float]]:
    """Parse Prometheus exposition text into
    ``{metric_name: {sorted-label-items-tuple: value}}`` — enough to
    round-trip :meth:`MetricsRegistry.to_prometheus` output and to
    assert on scraped bench artifacts; not a full openmetrics parser."""
    out: Dict[str, Dict[Tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError("unparseable metrics line: %r" % line)
        labels = tuple(sorted(
            (k, _unescape(v))
            for k, v in _LABEL_PAIR_RE.findall(m.group("labels") or "")))
        out.setdefault(m.group("name"), {})[labels] = float(m.group("value"))
    return out


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every raft_tpu layer reports into."""
    return _default
