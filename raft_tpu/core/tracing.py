"""Tracing / profiling ranges — TPU analog of NVTX ranges.

The reference wraps NVTX push/pop ranges in RAII helpers with printf-style
messages, compiled out unless enabled (cpp/include/raft/common/nvtx.hpp:17-60,
common/detail/nvtx.hpp:157-201).  On TPU the equivalent is the XLA/JAX
profiler: ``jax.profiler.TraceAnnotation`` shows up on the host timeline and
``jax.named_scope`` attaches names to the lowered HLO.  Ranges are cheap but
can be disabled globally (the NVTX=OFF analog) via :func:`set_enabled` or the
``RAFT_TPU_TRACING`` environment variable ("0" disables).

Event counters: the resilience layer (comms retry / abort / recovery,
see :mod:`raft_tpu.comms.resilience`) reports every event both as a
trace span and as a named monotonic counter.  Counters are always on —
they are a few dict ops, they feed health dashboards and tests, and
unlike spans they must not disappear when profiling is off.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Iterator, List

import jax

_enabled = os.environ.get("RAFT_TPU_TRACING", "1") != "0"
# imperative ranges nest per thread: a watchdog thread's push/pop must
# not close the main thread's open ranges (PR 1 regression — the comms
# resilience watchdog popped main-thread ranges off a process-global
# list)
_ranges = threading.local()
_counters: Dict[str, int] = {}
_counter_lock = threading.Lock()


def _range_stack() -> List[object]:
    stack = getattr(_ranges, "stack", None)
    if stack is None:
        stack = _ranges.stack = []
    return stack


def set_enabled(on: bool) -> None:
    """Globally enable/disable tracing ranges (CMake NVTX flag analog)."""
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def annotate(fmt: str, *args) -> Iterator[None]:
    """Scoped trace range (analog of nvtx::range RAII, common/nvtx.hpp:60).

    Printf-style message formatting mirrors the reference's
    ``push_range("name %d", i)`` usage.
    """
    if not _enabled:
        yield
        return
    name = fmt % args if args else fmt
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def range_push(fmt: str, *args) -> None:
    """Imperative push (analog of nvtx::push_range, common/nvtx.hpp:40).

    Enters both the host-timeline range (``TraceAnnotation``) and
    ``jax.named_scope`` — the same pair :func:`annotate` uses — so
    imperative and scoped ranges produce consistent HLO names for any
    tracing that happens between push and pop."""
    if not _enabled:
        return
    name = fmt % args if args else fmt
    ann = jax.profiler.TraceAnnotation(name)
    scope = jax.named_scope(name)
    ann.__enter__()
    scope.__enter__()
    _range_stack().append((ann, scope))


def range_pop() -> None:
    """Imperative pop (analog of nvtx::pop_range, common/nvtx.hpp:50).

    Pops regardless of the enabled flag: an already-entered range must be
    closed even if tracing was disabled between push and pop, or the
    profiler range leaks and later pops close the wrong ranges.
    """
    stack = _range_stack()
    if not stack:
        return
    ann, scope = stack.pop()
    scope.__exit__(None, None, None)
    ann.__exit__(None, None, None)


# ---------------------------------------------------------------------- #
# event counters (resilience/observability; always on, thread-safe —
# watchdog threads increment concurrently with the main thread)
# ---------------------------------------------------------------------- #
def counter_inc(name: str, n: int = 1) -> int:
    """Increment the named monotonic counter, returning the new value."""
    with _counter_lock:
        _counters[name] = _counters.get(name, 0) + n
        return _counters[name]


def get_counter(name: str) -> int:
    with _counter_lock:
        return _counters.get(name, 0)


def counters() -> Dict[str, int]:
    """Snapshot of every counter (copy; safe to iterate/serialize)."""
    with _counter_lock:
        return dict(_counters)


def reset_counters() -> None:
    """Zero all counters (test isolation / stats-window rollover)."""
    with _counter_lock:
        _counters.clear()


@contextlib.contextmanager
def event(name: str, fmt: str = "", *args) -> Iterator[None]:
    """Span + counter for one resilience event: increments ``name`` and
    opens an :func:`annotate` range carrying the formatted detail."""
    counter_inc(name)
    detail = (fmt % args) if args else fmt
    with annotate("%s%s" % (name, " " + detail if detail else "")):
        yield
