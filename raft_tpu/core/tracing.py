"""Tracing / profiling ranges — TPU analog of NVTX ranges.

The reference wraps NVTX push/pop ranges in RAII helpers with printf-style
messages, compiled out unless enabled (cpp/include/raft/common/nvtx.hpp:17-60,
common/detail/nvtx.hpp:157-201).  On TPU the equivalent is the XLA/JAX
profiler: ``jax.profiler.TraceAnnotation`` shows up on the host timeline and
``jax.named_scope`` attaches names to the lowered HLO.  Ranges are cheap but
can be disabled globally (the NVTX=OFF analog) via :func:`set_enabled` or the
``RAFT_TPU_TRACING`` environment variable ("0" disables).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, List

import jax

_enabled = os.environ.get("RAFT_TPU_TRACING", "1") != "0"
_range_stack: List[object] = []


def set_enabled(on: bool) -> None:
    """Globally enable/disable tracing ranges (CMake NVTX flag analog)."""
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def annotate(fmt: str, *args) -> Iterator[None]:
    """Scoped trace range (analog of nvtx::range RAII, common/nvtx.hpp:60).

    Printf-style message formatting mirrors the reference's
    ``push_range("name %d", i)`` usage.
    """
    if not _enabled:
        yield
        return
    name = fmt % args if args else fmt
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def range_push(fmt: str, *args) -> None:
    """Imperative push (analog of nvtx::push_range, common/nvtx.hpp:40)."""
    if not _enabled:
        return
    name = fmt % args if args else fmt
    cm = jax.profiler.TraceAnnotation(name)
    cm.__enter__()
    _range_stack.append(cm)


def range_pop() -> None:
    """Imperative pop (analog of nvtx::pop_range, common/nvtx.hpp:50).

    Pops regardless of the enabled flag: an already-entered range must be
    closed even if tracing was disabled between push and pop, or the
    profiler range leaks and later pops close the wrong ranges.
    """
    if not _range_stack:
        return
    cm = _range_stack.pop()
    cm.__exit__(None, None, None)
