"""Core runtime: resource handle, errors, tracing, small integer utilities.

TPU-native equivalent of the reference's layer-1 core
(cpp/include/raft/handle.hpp, error.hpp, cudart_utils.h, cuda_utils.cuh,
pow2_utils.cuh, integer_utils.h, common/nvtx.hpp).
"""

from raft_tpu.core.error import (
    AllocationError,
    CommAbortedError,
    CommError,
    CommTimeoutError,
    LogicError,
    RaftError,
    expects,
    fail,
)
from raft_tpu.core.handle import Handle
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import default_profiler, profiled, profiled_jit
from raft_tpu.core.tracing import annotate, range_pop, range_push
from raft_tpu.core.utils import (
    Pow2,
    align_down,
    align_to,
    ceildiv,
    is_pow2,
    log2,
)

__all__ = [
    "RaftError",
    "LogicError",
    "AllocationError",
    "CommError",
    "CommAbortedError",
    "CommTimeoutError",
    "expects",
    "fail",
    "Handle",
    "annotate",
    "range_push",
    "range_pop",
    "default_registry",
    "default_profiler",
    "profiled",
    "profiled_jit",
    "Pow2",
    "ceildiv",
    "align_to",
    "align_down",
    "is_pow2",
    "log2",
]
