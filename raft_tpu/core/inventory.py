"""XLA program cost inventory: per-executable flops/bytes/footprint.

The compile-cache accounting (:func:`raft_tpu.core.profiler.
compile_cache_stats`) answers *when* a program compiled and how long
the compile took; this module answers *what the compiler thinks the
program costs*: every executable produced at :func:`profiled_jit`'s
AOT lower/compile seam is interrogated once — ``compiled.
cost_analysis()`` (flops, bytes accessed) and ``compiled.
memory_analysis()`` (argument / output / temp footprints) — and the
answers are kept in a process-wide inventory keyed exactly like the
compile cache: (fn, input-aval key).

Why it matters for serving (docs/OBSERVABILITY.md "Ops plane"): after
``warmup()`` the executable set is CLOSED (the zero-post-warmup-
compiles invariant), so the inventory is a complete static picture of
the serving working set — summing the per-program footprints gives
the first device-capacity number the stack has ("how much HBM do my
warmed programs pin"), and dividing a program's flops by its measured
execution time gives a roofline-style achieved-throughput figure per
executable family (``tools/metrics_report.py`` renders both).

Everything here is host-side Python over numbers the compiler already
produced: capturing an entry costs one dict walk at compile time (a
cache miss — never the steady-state hot path), reading the inventory
costs a lock + dict copy.  The module never imports jax — the
``compiled`` object is handed in by the profiler — so the ops-plane
handlers can read it under the same no-jax static ban as every other
scrape (``ci/style_check.py`` ``ops-jax-ban``).

Metrics (labels ``fn``, ``entry`` — ``entry`` is a short stable hash
of the shape key, full detail in :func:`snapshot`):

- ``raft_tpu_program_flops``      — cost-model flop count
- ``raft_tpu_program_bytes``      — cost-model bytes accessed
- ``raft_tpu_program_hbm_bytes``  — argument+output+temp footprint

Backends that cannot answer (``cost_analysis`` raising, absent
``memory_analysis``) record zeros rather than failing the compile —
the inventory is observability, never a correctness gate.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Optional

from raft_tpu.core import metrics as _metrics

__all__ = [
    "note_compiled", "snapshot", "summary", "reset", "entry_count",
]

_lock = threading.Lock()
# fn_name -> {key_repr: entry dict}
_entries: Dict[str, Dict[str, dict]] = {}


def _slug(key_repr: str) -> str:
    """Short stable id for one (fn, shape) entry — the ``entry`` metric
    label (full key reprs are label-hostile: long, brace-heavy)."""
    return hashlib.sha1(key_repr.encode("utf-8")).hexdigest()[:10]


def _cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one flat dict.
    jax returns a list with one dict per module on some versions, a
    plain dict on others, None/raise where the backend cannot say."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def _memory_analysis(compiled):
    try:
        return compiled.memory_analysis()
    except Exception:
        return None


def note_compiled(fn_name: str, key, compiled) -> Optional[dict]:
    """Record one freshly AOT-compiled executable's cost picture.

    Called by :func:`raft_tpu.core.profiler.profiled_jit` on its
    compile-cache miss path (the one place executables are born); the
    lazy fallback path has no ``Compiled`` object and records nothing.
    Never raises — a backend that cannot be interrogated must not turn
    a working compile into a failure.
    """
    try:
        key_repr = repr(key)
        ca = _cost_analysis(compiled)
        ma = _memory_analysis(compiled)

        def _f(d, name):
            try:
                return float(d.get(name, 0.0) or 0.0)
            except (TypeError, ValueError):
                return 0.0

        arg_b = out_b = tmp_b = code_b = 0.0
        if ma is not None:
            arg_b = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
            out_b = float(getattr(ma, "output_size_in_bytes", 0) or 0)
            tmp_b = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
            code_b = float(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0)
        entry = {
            "entry": _slug(key_repr),
            "flops": _f(ca, "flops"),
            "bytes_accessed": _f(ca, "bytes accessed"),
            "argument_bytes": arg_b,
            "output_bytes": out_b,
            "temp_bytes": tmp_b,
            "code_bytes": code_b,
            # the capacity number: what this executable pins while it
            # runs (arguments in, outputs out, temps during)
            "hbm_bytes": arg_b + out_b + tmp_b,
        }
        with _lock:
            _entries.setdefault(fn_name, {})[key_repr] = entry
        reg = _metrics.default_registry()
        for mname, val, help in (
                ("raft_tpu_program_flops", entry["flops"],
                 "XLA cost-model flop count per compiled executable"),
                ("raft_tpu_program_bytes", entry["bytes_accessed"],
                 "XLA cost-model bytes accessed per compiled "
                 "executable"),
                ("raft_tpu_program_hbm_bytes", entry["hbm_bytes"],
                 "argument+output+temp device footprint per compiled "
                 "executable")):
            reg.gauge(mname, help=help, labels=("fn", "entry")).labels(
                fn=fn_name, entry=entry["entry"]).set(val)
        return entry
    except Exception:
        # observability must never fail the compile it observes
        return None


def snapshot() -> Dict[str, Dict[str, dict]]:
    """Plain-dict copy: ``{fn: {key_repr: entry}}`` (every entry also
    carries its short ``entry`` slug — the metric-label join key)."""
    with _lock:
        return {fn: {k: dict(e) for k, e in keys.items()}
                for fn, keys in _entries.items()}


def entry_count() -> int:
    with _lock:
        return sum(len(keys) for keys in _entries.values())


def summary() -> dict:
    """Per-fn rollup + the device-capacity line: program counts, the
    largest single-program cost, and the summed footprint of every
    inventoried executable (after warmup: the whole serving working
    set; docs/OBSERVABILITY.md "Ops plane")."""
    snap = snapshot()
    per_fn = {}
    total_hbm = 0.0
    total_programs = 0
    for fn, keys in sorted(snap.items()):
        flops = [e["flops"] for e in keys.values()]
        hbm = sum(e["hbm_bytes"] for e in keys.values())
        per_fn[fn] = {
            "programs": len(keys),
            "max_flops": max(flops) if flops else 0.0,
            "total_flops": sum(flops),
            "total_bytes_accessed": sum(
                e["bytes_accessed"] for e in keys.values()),
            "total_hbm_bytes": hbm,
        }
        total_hbm += hbm
        total_programs += len(keys)
    return {"programs": total_programs,
            "total_hbm_bytes": total_hbm,
            "per_fn": per_fn}


def reset() -> None:
    """Drop every inventoried entry (test isolation).  Gauges already
    published stay in the registry until its own reset — the registry
    owns metric lifetime, the inventory owns the detail dicts."""
    with _lock:
        _entries.clear()
