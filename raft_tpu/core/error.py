"""Error types and assertion helpers.

TPU-native analog of the reference's exception machinery
(cpp/include/raft/error.hpp): ``raft::exception`` collects a stack trace at
construction (error.hpp:28-92) and the ``RAFT_EXPECTS`` / ``RAFT_FAIL``
macros (error.hpp:132,148) raise it with a formatted message.  Python
exceptions already carry tracebacks, but we additionally capture the stack
at construction time so errors raised from inside async XLA dispatch still
point at the call site.
"""

from __future__ import annotations

import traceback


class RaftError(RuntimeError):
    """Exception with a captured construction-site stack trace.

    Mirrors ``raft::exception`` (reference error.hpp:28): the message is
    augmented with the stack collected where the error was *created*, which
    matters when the raise happens later (e.g. out of an async callback).
    """

    def __init__(self, message: str, collect_stack: bool = True):
        self.raw_message = message
        if collect_stack:
            stack = "".join(traceback.format_stack()[:-1])
            message = f"{message}\nObtained stack trace:\n{stack}"
        super().__init__(message)


class LogicError(RaftError):
    """Invariant violation (analog of raft::logic_error, error.hpp:94)."""


class AllocationError(RaftError):
    """A buffer allocation failed (the analog of the reference's
    ``rmm::bad_alloc`` surfacing through ``RAFT_TRY``).  Carries the
    context an OOM post-mortem needs: how much was asked for and how
    much this library already holds live.

    Attributes
    ----------
    requested_bytes:
        Size of the allocation that failed.
    live_bytes:
        raft_tpu-accounted live buffer bytes at failure time (see
        :mod:`raft_tpu.mr.buffer` accounting; XLA's own heap is not
        included).
    """

    def __init__(self, message: str, requested_bytes: int, live_bytes: int):
        self.requested_bytes = int(requested_bytes)
        self.live_bytes = int(live_bytes)
        super().__init__(
            "%s (requested %d bytes; %d raft_tpu buffer bytes live)"
            % (message, self.requested_bytes, self.live_bytes))


class ServiceOverloadError(RaftError):
    """Admission control rejected a request: the serving queue (or the
    shedding tenant's share of it) is at its configured depth cap
    (:mod:`raft_tpu.serve` — the analog of a load-balancer shedding
    rather than queueing unboundedly; see docs/SERVING.md).  Callers
    should back off ``retry_after_s`` and resubmit, or raise capacity
    (``serve_queue_cap``).

    Matches the :class:`ServiceUnavailableError` taxonomy — both carry
    ``retry_after_s`` so callers back off uniformly whether the service
    is *full* (this error) or *broken/healing* (that one).

    Attributes
    ----------
    queue_depth:
        Requests queued at rejection time (the shedding tenant's depth
        when a per-tenant cap shed).
    queue_cap:
        The cap that shed (the tenant's share when tenancy is active).
    tenant:
        Name of the tenant whose quota shed the request, or None for a
        shed with no tenant dimension (e.g. a full ANN delta segment).
    retry_after_s:
        Hint: estimated seconds until the queue drains enough to admit
        again (0.0 when unknown).
    """

    def __init__(self, message: str, queue_depth: int, queue_cap: int,
                 tenant: "str | None" = None,
                 retry_after_s: float = 0.0):
        self.queue_depth = int(queue_depth)
        self.queue_cap = int(queue_cap)
        self.tenant = None if tenant is None else str(tenant)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            "%s (queue depth %d at cap %d%s retry_after_s=%.3f)"
            % (message, self.queue_depth, self.queue_cap,
               "" if self.tenant is None else " tenant=%s" % self.tenant,
               self.retry_after_s))


class ServiceUnavailableError(RaftError):
    """The service cannot accept requests *at all* right now — its
    circuit breaker is open (too many consecutive/windowed batch
    failures), its worker thread has died, or a recovery is in progress
    (:mod:`raft_tpu.serve.resilience`).  Distinct from
    :class:`ServiceOverloadError`: overload means "healthy but full —
    back off briefly"; unavailable means "broken or healing — shed now
    and retry after ``retry_after_s``" (queueing into a broken worker
    would only convert the outage into client timeouts).

    Attributes
    ----------
    service:
        Name of the service that shed the request.
    reason:
        Short machine-readable cause (``"breaker_open"``,
        ``"worker_dead"``, ``"recovering"``).
    retry_after_s:
        Hint: seconds until the service may admit again (0.0 when
        unknown — e.g. a dead worker awaiting an explicit
        ``restart()``/recovery).
    """

    def __init__(self, message: str, service: str, reason: str,
                 retry_after_s: float = 0.0):
        self.service = str(service)
        self.reason = str(reason)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            "%s (service=%s reason=%s retry_after_s=%.3f)"
            % (message, self.service, self.reason, self.retry_after_s))


class DataCorruptionError(RaftError):
    """Persisted serving state failed an integrity check
    (:mod:`raft_tpu.persist`): a snapshot manifest, array payload, or
    interior write-ahead-log record whose stored checksum does not
    match its bytes (docs/PERSISTENCE.md).  Never retried and never
    tolerated silently — a corrupt region must fail loudly rather than
    serve wrong distances.  (A *torn trailing* WAL record — an append
    cut short by the crash itself — is the one tolerated case and does
    not raise; see the WAL replay contract.)

    Attributes
    ----------
    path:
        File holding the corrupt region.
    offset:
        Byte offset of the failing region within ``path`` (None when
        the whole file is the unit, e.g. a manifest).
    expected_crc / actual_crc:
        The stored checksum vs the checksum of the bytes actually read
        (None when the failure precedes checksumming, e.g. a bad
        record magic or unparseable manifest).
    """

    def __init__(self, message: str, path: str,
                 offset: "int | None" = None,
                 expected_crc: "int | None" = None,
                 actual_crc: "int | None" = None):
        self.path = str(path)
        self.offset = None if offset is None else int(offset)
        self.expected_crc = (None if expected_crc is None
                             else int(expected_crc))
        self.actual_crc = None if actual_crc is None else int(actual_crc)
        where = self.path if self.offset is None else (
            "%s @ byte %d" % (self.path, self.offset))
        crcs = ("" if self.expected_crc is None
                else " expected_crc=0x%08x actual_crc=0x%08x"
                % (self.expected_crc,
                   0 if self.actual_crc is None else self.actual_crc))
        super().__init__("%s (%s%s)" % (message, where, crcs))


class CommError(RaftError):
    """Communicator failure (analog of the reference's NCCL/UCX error
    surfacing: ``RAFT_NCCL_TRY`` / the ERROR arm of ``status_t``,
    comms.hpp:41).  Transient instances are retryable by
    :class:`raft_tpu.comms.resilience.RetryPolicy`; a communicator that
    exhausts its retries latches aborted."""


class CommAbortedError(CommError):
    """The communicator is latched aborted (the ``ncclCommAbort``
    contract, std_comms.hpp:443-475: once any participant observes a
    failure the communicator is permanently unusable).  Every subsequent
    verb fails fast with this error; recovery requires rebuilding the
    communicator (``Comms.recover``)."""


class CommTimeoutError(CommError):
    """A communicator verb (or the multi-host bootstrap) exceeded its
    watchdog deadline (the analog of the reference's UCX progress-loop
    timeout abort, std_comms.hpp:234-298)."""


# Deterministic caller bugs: invariant violations (RAFT_EXPECTS) plus the
# Python-level errors JAX tracing raises for bad shapes/indices/dtypes.
# Shared by the comms retry policy (never retried) and the verb layer
# (never poisons the communicator) so the two taxonomies cannot drift.
CALLER_BUG_ERRORS = (LogicError, TypeError, ValueError, IndexError, KeyError)


def expects(cond: bool, fmt: str, *args) -> None:
    """Raise :class:`LogicError` unless ``cond`` holds.

    Analog of ``RAFT_EXPECTS(cond, fmt, ...)`` (reference error.hpp:132).
    ``fmt`` is %-formatted with ``args`` to match the macro's printf style.
    """
    if not cond:
        raise LogicError(fmt % args if args else fmt)


def fail(fmt: str, *args) -> None:
    """Unconditionally raise :class:`LogicError`.

    Analog of ``RAFT_FAIL(fmt, ...)`` (reference error.hpp:148).
    """
    raise LogicError(fmt % args if args else fmt)
