"""Resource handle: the TPU-native ``raft::handle_t``.

The reference's ``handle_t`` (cpp/include/raft/handle.hpp:49-285) is the
single resource context threaded through every primitive: device id, main
stream, a stream pool for intra-process parallelism, lazily-created vendor
library handles, an injected communicator plus named sub-communicators, and
cached device properties.

TPU mapping:

- CUDA device            → a ``jax.Device`` (and optionally a
                           ``jax.sharding.Mesh`` for SPMD primitives).
- CUDA stream            → JAX async dispatch: every op is enqueued
                           asynchronously; a ``Stream`` here is a handle that
                           tracks the arrays dispatched "on" it so
                           ``sync_stream`` can block on exactly that work.
- stream pool            → pool of such trackers; XLA overlaps independent
                           computations on its own, so the pool preserves the
                           reference API (handle.hpp:148-227) while mapping
                           to concurrent async dispatch.
- cublas/cusolver/etc.   → XLA: no explicit handles needed; the analogous
                           lazily-built resource is the jit executable cache,
                           which JAX maintains per (fn, shapes, device).
- comms_t injection      → :meth:`set_comms` / :meth:`get_comms` and named
                           sub-communicators (handle.hpp:229-252).
- cudaDeviceProp         → :meth:`get_device_properties` summarising the
                           device kind / memory / core counts.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from raft_tpu.core.error import CommAbortedError, RaftError, expects


class Stream:
    """Async work tracker standing in for a CUDA stream.

    JAX dispatch is asynchronous by default (the stream-ordered model the
    reference assumes); a ``Stream`` records the output arrays of work
    "enqueued on" it so :meth:`sync` blocks on precisely that work, matching
    ``cudaStreamSynchronize`` granularity.
    """

    def __init__(self, name: str = "stream"):
        self.name = name
        self._pending: List[Any] = []

    def record(self, *arrays) -> None:
        """Associate dispatched work (its output arrays) with this stream."""
        self._pending.extend(arrays)

    def sync(self) -> None:
        """Block until all recorded work is complete.

        The pending list is cleared even when blocking *fails*: keeping
        the poisoned arrays would make every later ``sync`` re-raise on
        stale work (a CUDA stream does not replay a past fault either —
        ``cudaStreamSynchronize`` reports it once and the stream moves
        on).  The failure is wrapped in :class:`RaftError` so async XLA
        dispatch errors surface through the library's taxonomy.
        """
        if not self._pending:
            return
        try:
            jax.block_until_ready(self._pending)
        except RaftError:
            raise
        except Exception as e:
            raise RaftError(
                "stream '%s' sync failed on dispatched work: %s"
                % (self.name, e)) from e
        finally:
            self._pending.clear()


class Handle:
    """Central resource context passed to every primitive.

    Parameters
    ----------
    device:
        The accelerator device to target.  Defaults to ``jax.devices()[0]``.
    n_streams:
        Size of the stream pool (reference handle.hpp:80 ctor arg
        ``stream_pool``).  0 means no pool.
    mesh:
        Optional ``jax.sharding.Mesh`` for SPMD primitives; the TPU-native
        extension of the reference's comms-carrying handle.
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        n_streams: int = 0,
        mesh: Optional[jax.sharding.Mesh] = None,
        profiler=None,
    ):
        from raft_tpu.core.profiler import default_profiler

        self.device = device if device is not None else jax.devices()[0]
        self._stream = Stream("main")
        self._stream_pool = [Stream(f"pool{i}") for i in range(n_streams)]
        self._comms = None
        self._subcomms: Dict[str, Any] = {}
        self.mesh = mesh
        # scoped profiler: primitives threaded through this handle (and
        # session snapshots) share it; defaults to the process profiler
        # so handle-less primitive calls land in the same report
        self.profiler = (profiler if profiler is not None
                         else default_profiler())

    # ------------------------------------------------------------------ #
    # streams (reference handle.hpp:148-227)
    # ------------------------------------------------------------------ #
    def get_stream(self) -> Stream:
        """Main stream (reference ``get_stream``, handle.hpp:148)."""
        return self._stream

    def is_stream_pool_initialized(self) -> bool:
        return len(self._stream_pool) > 0

    def get_stream_pool_size(self) -> int:
        return len(self._stream_pool)

    def get_stream_from_stream_pool(self, idx: int = 0) -> Stream:
        """Pool stream by index (reference handle.hpp:186)."""
        expects(
            len(self._stream_pool) > 0,
            "ERROR: rmm::cuda_stream_pool was not initialized",
        )
        return self._stream_pool[idx % len(self._stream_pool)]

    def get_next_usable_stream(self, idx: int = 0) -> Stream:
        """Pool stream if a pool exists, else the main stream
        (reference handle.hpp:205-214)."""
        if self._stream_pool:
            return self._stream_pool[idx % len(self._stream_pool)]
        return self._stream

    def sync_stream(self, stream: Optional[Stream] = None) -> None:
        """Synchronize one stream (reference ``sync_stream``, handle.hpp:158)."""
        (stream or self._stream).sync()

    def sync_stream_pool(self) -> None:
        """Synchronize every pool stream (reference handle.hpp:216)."""
        for s in self._stream_pool:
            s.sync()

    def wait_stream_pool_on_stream(self) -> None:
        """Order pool work after main-stream work (reference handle.hpp:221).

        JAX data dependencies provide this ordering automatically; syncing
        the main stream is the conservative host-side equivalent.
        """
        self._stream.sync()

    # ------------------------------------------------------------------ #
    # comms (reference handle.hpp:229-252)
    # ------------------------------------------------------------------ #
    def set_comms(self, comms) -> None:
        self._comms = comms

    def get_comms(self):
        expects(self._comms is not None, "ERROR: Communicator was not initialized on the handle")
        if getattr(self._comms, "aborted", False):
            raise CommAbortedError(
                "communicator on this handle is latched aborted; rebuild "
                "it (Comms.recover()) before issuing collectives")
        return self._comms

    def comms_initialized(self) -> bool:
        return self._comms is not None

    def set_subcomm(self, key: str, comms) -> None:
        self._subcomms[key] = comms

    def get_subcomm(self, key: str):
        expects(
            key in self._subcomms,
            "%s was not found in subcommunicators.",
            key,
        )
        return self._subcomms[key]

    # ------------------------------------------------------------------ #
    # device properties (reference handle.hpp:254-262)
    # ------------------------------------------------------------------ #
    def get_device(self) -> jax.Device:
        return self.device

    def get_device_properties(self) -> Dict[str, Any]:
        d = self.device
        props: Dict[str, Any] = {
            "platform": d.platform,
            "device_kind": d.device_kind,
            "id": d.id,
            "process_index": d.process_index,
        }
        try:
            stats = d.memory_stats()
            if stats:
                props.update(
                    bytes_limit=stats.get("bytes_limit"),
                    bytes_in_use=stats.get("bytes_in_use"),
                )
        except Exception:
            pass
        return props


def takes_handle(fn):
    """Give a primitive the reference's ``handle_t&`` argument contract.

    Every reference primitive takes a handle first (handle.hpp:49) and
    enqueues its work on ``handle.get_stream()``.  On TPU the handle's
    role at primitive granularity is completion tracking, so instead of
    hand-writing the same plumbing into ~60 thin XLA delegations, this
    decorator appends an optional ``handle=None`` keyword and records
    every array output on the handle's main stream — after which
    ``sync_stream`` / ``stream_syncer`` cover the call exactly as they
    do for the hand-threaded primitives (pairwise/knn/spectral/...).

    It is also the observability seam for those ~60 primitives: the
    call runs inside a ``<layer>.<name>`` profiler span feeding the
    ``raft_tpu_<layer>_<name>_seconds`` timer (docs/OBSERVABILITY.md),
    with layer/name derived from the function's module path.
    """
    import functools

    from raft_tpu.core.profiler import default_profiler

    # "raft_tpu.linalg.gemm" -> layer "linalg"
    mod_parts = (fn.__module__ or "").split(".")
    layer = mod_parts[1] if len(mod_parts) > 1 else "core"
    span_name = "%s.%s" % (layer, fn.__name__)

    @functools.wraps(fn)
    def wrapper(*args, handle=None, **kwargs):
        prof = (handle.profiler if handle is not None
                and getattr(handle, "profiler", None) is not None
                else default_profiler())
        with prof.span(span_name, layer=layer):
            out = fn(*args, **kwargs)
        if handle is not None:
            record_on_handle(
                handle,
                *[x for x in jax.tree_util.tree_leaves(out)
                  if hasattr(x, "dtype")])
        return out

    doc = wrapper.__doc__ or ""
    wrapper.__doc__ = doc + (
        "\n\n    ``handle``: optional resource context (reference "
        "``handle_t&`` first arg);\n    outputs are recorded on its main "
        "stream for ``sync_stream`` coverage.\n")
    return wrapper


def record_on_handle(handle: Optional[Handle], *arrays) -> None:
    """Associate dispatched work with a handle's main stream so
    ``handle.sync_stream()`` blocks on it — the TPU analog of the
    reference's primitives enqueuing on ``handle.get_stream()``.
    No-op when ``handle`` is None (every primitive's default)."""
    if handle is not None:
        handle.get_stream().record(*arrays)


class stream_syncer:
    """RAII-style scope that syncs the handle on exit
    (reference ``stream_syncer``, handle.hpp:311)."""

    def __init__(self, handle: Handle):
        self.handle = handle

    def __enter__(self) -> Handle:
        return self.handle

    def __exit__(self, *exc) -> None:
        self.handle.sync_stream()
        self.handle.sync_stream_pool()
