"""Candidate registry: every impl knob self-describes in ONE place.

The reference hand-specializes its kernel dispatch per GPU arch
(selection_faiss.cuh's k-template ladder, ann_common.h's algo enums);
raft_tpu's port accumulated the same problem as per-file whitelists —
``select_k`` carried its own impl tuple, ``SparseMatrix.__init__`` its
own spmv guard, the fused-kNN merge its own pin plumbing.  This module
is the replacement: every implementation choice registers its

    (op, knob, candidates, legality(value, ctx))

here, and consumers resolve/validate through :func:`resolve` /
:func:`check` instead of carrying local literals.  The registry is also
the search space of the bench-driven sweep (``tools/autotune.py``): the
sweep enumerates :func:`specs`, times every candidate that is legal for
a cell, and persists winners to the tuning table that
:func:`raft_tpu.config.tuned` consults between env and default
(docs/TUNING.md "Bench-driven autotuning").

Vocabulary
----------
cell
    One (backend, op, shape-class, dtype) point of the tuning space.
shape class
    :func:`shape_class`: the relevant dims of a call site, each rounded
    to its nearest power of two — the quantization that lets a sweep at
    (n=131072, k=128) answer a query at (n=100000, k=100).
legality
    ``legality(value, ctx) -> Optional[str]``: None when the candidate
    is legal for the cell described by ``ctx`` (dims, ``dtype``,
    ``purpose``), else a human reason.  ``purpose`` is ``"use"``
    (consumer resolution — only genuine correctness limits apply) or
    ``"sweep"`` (the autotuner additionally rejects candidates that are
    not production-meaningful on this backend, e.g. interpreted Pallas
    kernels off-TPU).
arg-only candidate
    Legal only as an explicit function argument, never from
    config/env/table — e.g. the ``knn_tile_merge`` ``"skip"``
    attribution probe that returns wrong results by design.
no-sweep candidate
    Settable, but excluded from the timed sweep because a time-only
    comparison would be unfair — the deliberately approximate modes
    (``approx95``) and the precision-caveated ``cumsum`` SpMV.

Error contract: every validation failure raises
:class:`~raft_tpu.core.error.LogicError` through ONE message shape
(:func:`check`) naming the site, the knob, the rejected value, the
legal set, and why it is illegal for this cell — the scattered
per-file messages this registry replaced each said less.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Mapping, Optional, Tuple

from raft_tpu.core.error import LogicError

__all__ = [
    "register", "spec", "specs", "candidates", "check", "resolve",
    "legal_candidates", "shape_class", "backend_fingerprint",
    "fingerprint_slug",
]

# ctx -> None (legal) | reason string (illegal for this cell)
Legality = Callable[[str, Mapping], Optional[str]]


class KnobSpec:
    """One registered impl choice (module doc for the field semantics).

    ``config_knob`` — True when the knob resolves through
    :mod:`raft_tpu.config` (override/configure/env/table/default);
    False for registry-only knobs (``merge_select_impl``,
    ``mnmg_group_size``) whose default is pinned here precisely so a
    process-wide config change cannot reach them silently.
    ``dims`` — the ctx dims that define this knob's shape class (both
    the consumers and the sweep key cells on exactly these).
    """

    __slots__ = ("op", "knob", "candidates", "arg_only", "no_sweep",
                 "legality", "config_knob", "default", "auto_default",
                 "dims", "doc")

    def __init__(self, op, knob, candidates, *, arg_only=(),
                 no_sweep=None, legality=None, config_knob=True,
                 default=None, auto_default=None, dims=(), doc=""):
        self.op = op
        self.knob = knob
        self.candidates = tuple(candidates) if candidates else None
        self.arg_only = tuple(arg_only)
        self.no_sweep = dict(no_sweep or {})
        self.legality = legality
        self.config_knob = config_knob
        self.default = default
        # what an UNSET knob effectively runs (the consumer's auto
        # dispatch, e.g. fused_knn_impl None -> "xla"): the sweep's
        # comparison baseline for knobs whose config default is None
        self.auto_default = auto_default
        self.dims = tuple(dims)
        self.doc = doc

    def illegal_reason(self, value, ctx: Mapping) -> Optional[str]:
        """Why ``value`` is illegal for the cell ``ctx`` (None = legal).
        Membership (including the arg-only rule) first, then the
        spec's own legality predicate."""
        if self.candidates is not None:
            allowed = self.candidates + (
                self.arg_only if ctx.get("explicit") else ())
            if value not in allowed:
                if value in self.arg_only:
                    return ("argument-only (an attribution probe must "
                            "never be reachable from config/env/table)")
                return "unknown impl (not a registered candidate)"
        if ctx.get("purpose") == "sweep" and value in self.no_sweep:
            return self.no_sweep[value]
        if self.legality is not None:
            return self.legality(value, ctx)
        return None


_SPECS: Dict[str, KnobSpec] = {}


def register(op: str, knob: str, candidates, **kw) -> KnobSpec:
    """Register one impl choice (module doc).  Idempotent per knob name
    only in the sense that re-registration replaces — knobs are
    registered once, below, at import."""
    s = KnobSpec(op, knob, candidates, **kw)
    _SPECS[knob] = s
    return s


def spec(knob: str) -> KnobSpec:
    if knob not in _SPECS:
        raise LogicError(
            "raft_tpu.core.tuning: unknown knob %r (registered: %s)"
            % (knob, ", ".join(sorted(_SPECS))))
    return _SPECS[knob]


def specs() -> Tuple[KnobSpec, ...]:
    """Every registered spec — the sweep's search space."""
    return tuple(_SPECS[k] for k in sorted(_SPECS))


def candidates(knob: str) -> Tuple[str, ...]:
    """The config-settable candidate set of ``knob`` (the one source —
    consumer modules re-export THIS instead of a local literal)."""
    c = spec(knob).candidates
    return c if c is not None else ()


def _fmt_legal(s: KnobSpec, explicit: bool) -> str:
    if s.candidates is None:
        return "free-form"
    vals = s.candidates + (s.arg_only if explicit else ())
    return ", ".join(vals)


def check(knob: str, value, *, site: Optional[str] = None,
          explicit: bool = False, purpose: str = "use",
          dtype=None, **dims):
    """Validate ``value`` for ``knob`` at the cell described by
    ``dims``/``dtype``; returns the value or raises
    :class:`LogicError` in the shared message shape (module doc)."""
    s = spec(knob)
    ctx = _ctx(explicit=explicit, purpose=purpose, dtype=dtype, **dims)
    reason = s.illegal_reason(value, ctx)
    if reason is not None:
        raise LogicError(
            "%s: %s=%r is illegal for this cell (legal: %s) — %s"
            % (site or s.op, knob, value, _fmt_legal(s, explicit),
               reason))
    return value


def legal_candidates(knob: str, *, purpose: str = "use", dtype=None,
                     **dims):
    """(candidate, reason) pairs: reason None = legal for this cell.
    The sweep driver's view of a cell's search space."""
    s = spec(knob)
    ctx = _ctx(explicit=False, purpose=purpose, dtype=dtype, **dims)
    return tuple((c, s.illegal_reason(c, ctx))
                 for c in (s.candidates or ()))


def resolve(knob: str, explicit=None, *, site: Optional[str] = None,
            dtype=None, **dims):
    """THE consumer entry point: explicit argument, else the config
    ladder (override → configure → env → tuning table → default) for
    config knobs, else the spec's pinned default — always validated.

    A *table* answer that is illegal for the real cell (the table was
    swept at a coarser class than this call) silently falls back to
    the built-in default: the table is advisory, never a new way to
    crash a call that used to work.  Returns None only for
    unset-default knobs (``fused_knn_impl`` auto).
    """
    s = spec(knob)
    site = site or s.op
    if explicit is not None:
        return check(knob, explicit, site=site, explicit=True,
                     dtype=dtype, **dims)
    if not s.config_knob:
        value = s.default
        if value is None:
            return None
        return check(knob, value, site=site, dtype=dtype, **dims)
    from raft_tpu import config

    value, layer = config.tuned(knob, op=s.op, dtype=_dtype_str(dtype),
                                dims=_class_dims(s, dims))
    if value is None:
        return None
    if layer == "table":
        ctx = _ctx(explicit=False, purpose="use", dtype=dtype, **dims)
        if s.illegal_reason(value, ctx) is not None:
            # the lookup already counted a "hit"; record the discard
            # so the observability digest can report EFFECTIVE table
            # coverage (hits - discarded)
            config._count_table("discarded", knob)
            value = config.knob_default(knob)
            if value is None:
                return None
    return check(knob, value, site=site, dtype=dtype, **dims)


def _ctx(**kw) -> Mapping:
    d = {k: v for k, v in kw.items() if v is not None}
    d.setdefault("explicit", False)
    d.setdefault("purpose", "use")
    return d


def _dtype_str(dtype) -> Optional[str]:
    if dtype is None:
        return None
    try:
        import numpy as np

        return np.dtype(dtype).name
    except TypeError:
        return getattr(dtype, "name", None) or str(dtype)


def _class_dims(s: KnobSpec, dims: Mapping) -> Dict[str, int]:
    """Restrict a consumer's ctx dims to the spec's class dims so the
    lookup key and the sweep key cannot skew on extra context."""
    return {k: int(v) for k, v in dims.items()
            if k in s.dims and v is not None}


# --------------------------------------------------------------------- #
# shape classes + backend fingerprint (the tuning-table key space)
# --------------------------------------------------------------------- #
def shape_class(dims: Mapping) -> str:
    """Canonical shape-class string: each dim rounded to the nearest
    power of two (in log space), formatted ``k=v`` sorted by name.
    Empty dims → ``"*"`` (the any-shape class).  Restriction to the
    spec's class dims happens in :func:`_class_dims` before this.

    Pow2 rounding is the whole mechanism: a sweep at (n=131072, k=128)
    and a query at (n=100000, k=100) land in the SAME class, while
    n=8192 lands two classes away — coarse enough that a small swept
    grid covers real traffic, fine enough that the known winner flips
    (select_impl at k=100 vs k=10) stay separated.
    """
    items = []
    for name in sorted(dims):
        v = dims[name]
        if v is None:
            continue
        v = int(v)
        b = 0 if v <= 0 else 1 << max(0, round(math.log2(v)))
        items.append("%s=%d" % (name, b))
    return ",".join(items) if items else "*"


def backend_fingerprint() -> Dict[str, object]:
    """(platform, device kind, device count) of the live backend — the
    venue key a tuning table is valid for.  Imports jax lazily so the
    registry itself stays importable without a backend (the style lint
    and ``--dry-run`` sweeps parse it statically)."""
    import jax

    devs = jax.devices()
    return {
        "platform": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "none",
        "device_count": len(devs),
    }


def fingerprint_slug(fp: Mapping) -> str:
    """Filesystem-safe name for a fingerprint (the checked-in table
    files under ``raft_tpu/tuning/`` are named by it)."""
    import re

    kind = re.sub(r"[^A-Za-z0-9]+", "-", str(fp["device_kind"])).strip("-")
    return "%s_%s_d%d" % (fp["platform"], kind.lower(),
                          int(fp["device_count"]))


# --------------------------------------------------------------------- #
# helpers shared by the legality predicates
# --------------------------------------------------------------------- #
def _is_float_dtype(dtype) -> Optional[bool]:
    """True/False when ``dtype`` is known, None when absent from ctx
    (legality is best-effort on the context it is given)."""
    if dtype is None:
        return None
    name = _dtype_str(dtype)
    return name.startswith(("float", "bfloat", "f8", "float8"))


def _off_tpu_sweep(ctx: Mapping) -> Optional[str]:
    """Sweep-only rejection of Pallas kernels off-TPU: they run through
    the interpreter there (a test vehicle, not a production candidate),
    so timing one against XLA would 'lose' by construction and waste
    most of the sweep budget doing it."""
    if ctx.get("purpose") != "sweep":
        return None
    from raft_tpu.core.utils import is_tpu_backend

    if not is_tpu_backend():
        return ("pallas kernels run interpreted off-TPU — a test "
                "vehicle, not a sweep candidate on this backend")
    return None


def _legal_select_impl(value, ctx):
    if value == "pallas":
        if ctx.get("k") is not None and int(ctx["k"]) > 128:
            return ("the fused select kernel caps k at 128 (bitonic "
                    "merge width); got k=%d" % int(ctx["k"]))
        if _is_float_dtype(ctx.get("dtype")) is False:
            return "the fused select kernel requires float keys"
        return _off_tpu_sweep(ctx)
    return None


def _legal_fused_knn(value, ctx):
    if value == "pallas":
        if ctx.get("k") is not None and int(ctx["k"]) > 128:
            return ("the fused kNN kernel caps k at 128 (bitonic merge "
                    "width); got k=%d — use impl='xla' or reduce k"
                    % int(ctx["k"]))
        return _off_tpu_sweep(ctx)
    return None


def _legal_knn_tile_merge(value, ctx):
    # every merge network lives inside the Pallas kernel: off-TPU the
    # whole knob is interpreter-only, so no candidate is sweepable there
    return _off_tpu_sweep(ctx)


# Best-effort VMEM budget for the block-shape legality checks: real
# v4/v5 cores carry 16 MiB; leave headroom for double-buffered DMA and
# the select scratch.  A knob that passes here can still be rejected by
# Mosaic on-chip — the predicate's job is to keep the sweep from timing
# obviously-doomed shapes, not to model the compiler.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _block_bytes(bq, bn, d, k):
    """Rough VMEM bytes of one fused-kNN grid step at (bq, bn): query +
    index tiles (f32, depth padded to the 128-lane multiple), the
    distance tile, and the running top-k scratch (kpad lanes, dist+idx)."""
    dp = -(-max(int(d), 1) // 128) * 128 if d and int(d) > 128 else 128
    kpad = 128
    if k:
        kpad = max(128, 1 << max(0, math.ceil(math.log2(int(k)))))
    return 4 * (bq * dp + bn * dp + bq * bn + 2 * bq * 2 * kpad)


def _legal_block(value, ctx, *, unit, companion_default, is_q):
    """Shared integer-ladder legality: parse, alignment, VMEM fit.

    The fit check uses the *companion* block's config default when the
    ctx doesn't carry it — each knob is swept independently, so the
    estimate is per-knob best-effort (module doc of the predicate
    constant above).  No off-TPU sweep rejection: the same tile shapes
    drive the ``xla_fused`` reference's geometry, so the ladder is a
    real (timeable) candidate set on every backend.
    """
    try:
        b = int(value)
    except (TypeError, ValueError):
        return "not an integer"
    if b < unit or b % unit != 0:
        return ("block shape %d must be a positive multiple of %d "
                "(%s)" % (b, unit,
                          "sublane rows" if unit == 8 else "lane width"))
    d = ctx.get("d")
    if d is not None:
        bq, bn = (b, companion_default) if is_q else (companion_default, b)
        need = _block_bytes(bq, bn, d, ctx.get("k"))
        if need > _VMEM_BUDGET_BYTES:
            return ("estimated VMEM %.1f MiB for (block_q=%d, block_n="
                    "%d, d=%s) exceeds the %.0f MiB budget"
                    % (need / 2**20, bq, bn, d,
                       _VMEM_BUDGET_BYTES / 2**20))
    return None


def _legal_knn_block_q(value, ctx):
    return _legal_block(value, ctx, unit=8, companion_default=1024,
                        is_q=True)


def _legal_knn_block_n(value, ctx):
    return _legal_block(value, ctx, unit=128, companion_default=256,
                        is_q=False)


def _legal_nn_block_n(value, ctx):
    # the 1-NN kernel keeps only a (bm, 128) running min — reuse the
    # kNN estimate with its k-free scratch (k absent from ctx)
    return _legal_block(value, ctx, unit=128, companion_default=256,
                        is_q=False)


def _legal_ivf_scan(value, ctx):
    if value in ("pallas", "pallas_bf16"):
        if ctx.get("k") is not None and int(ctx["k"]) > 128:
            return ("the fused IVF scan kernel caps k at 128 (bitonic "
                    "merge width); got k=%d — use impl='xla'"
                    % int(ctx["k"]))
        metric = ctx.get("metric")
        if metric is not None and str(metric) not in (
                "l2", "sqeuclidean", "euclidean", "l2sqrt"):
            return ("the fused IVF scan kernel implements the expanded "
                    "L2 family only; got metric=%r" % (metric,))
        return _off_tpu_sweep(ctx)
    return None


def _legal_fused_knn_xla_ref(value, ctx):
    if value == "xla_fused":
        # the XLA-composed fused twin (ops/knn_tile.fused_knn_xla)
        # shares the kernel's k <= 128 cap but runs everywhere (it IS
        # the off-TPU production fallback) — no off-TPU sweep rejection
        if ctx.get("k") is not None and int(ctx["k"]) > 128:
            return ("the fused kNN formulation caps k at 128 (bitonic "
                    "merge width); got k=%d — use impl='xla'"
                    % int(ctx["k"]))
        return None
    return _legal_fused_knn(value, ctx)


def _legal_group_size(value, ctx):
    try:
        g = int(value)
    except (TypeError, ValueError):
        return "not an integer"
    size = ctx.get("axis_size")
    if size is not None and not (1 <= g <= int(size)
                                 and int(size) % g == 0):
        return ("group_size=%d must divide the merge axis size %d "
                "(balanced two-level decomposition)" % (g, int(size)))
    return None


# --------------------------------------------------------------------- #
# the registry — every impl choice in the library, one block
# --------------------------------------------------------------------- #
register(
    "select_k", "select_impl",
    ("topk", "approx", "approx95", "chunked", "pallas"),
    legality=_legal_select_impl,
    no_sweep={"approx95": ("deliberately approximate (recall_target "
                           "0.95) — a time-only sweep must not trade "
                           "exactness silently")},
    dims=("n", "k"),
    doc="per-row top-k impl (spatial/select_k.py)")

register(
    "tiled_knn", "tile_merge", ("tile_topk", "direct"),
    dims=("n", "k"),
    doc="tile-scan kNN per-tile selection strategy (spatial/tiled_knn.py)")

register(
    "fused_knn_tile", "knn_tile_merge", ("merge", "fullsort", "sorttile"),
    arg_only=("skip",),
    legality=_legal_knn_tile_merge,
    dims=("n", "k"),
    doc="Pallas fused-kNN/select merge network (ops/knn_tile.py)")

register(
    "fused_l2_knn", "fused_knn_impl", ("xla", "pallas", "xla_fused"),
    legality=_legal_fused_knn_xla_ref,
    auto_default="xla",
    dims=("n", "k"),
    doc="fused L2 kNN path (spatial/fused_l2_knn.py): xla = tiled "
        "two-stage scan, pallas = fused kernel, xla_fused = "
        "XLA-composed emulation of the kernel (off-TPU fallback + "
        "bitwise oracle); unset = per-backend auto (currently xla "
        "everywhere, the r4 measured default)")

register(
    "fused_knn_tile", "knn_block_q", ("64", "128", "256", "512"),
    legality=_legal_knn_block_q,
    dims=("n", "k", "d"),
    doc="fused-kNN query-tile rows (ops/knn_tile.py + the xla_fused "
        "emulation's row-tile geometry); sublane-multiple integer "
        "ladder, VMEM-fit checked (docs/TUNING.md)")

register(
    "fused_knn_tile", "knn_block_n", ("256", "512", "1024", "2048",
                                      "4096"),
    legality=_legal_knn_block_n,
    dims=("n", "k", "d"),
    doc="fused-kNN index-tile columns (ops/knn_tile.py + the "
        "xla_fused emulation); lane-multiple integer ladder, VMEM-fit "
        "checked")

register(
    "fused_nn_tile", "nn_block_n", ("256", "512", "1024", "2048",
                                    "4096"),
    legality=_legal_nn_block_n,
    dims=("n", "d"),
    doc="fused 1-NN index-tile columns (ops/nn_tile.py, consumed by "
        "distance/fused_l2_nn.py); lane-multiple integer ladder")

register(
    "ivf_flat_search", "ivf_scan_impl", ("xla", "pallas",
                                         "pallas_bf16"),
    legality=_legal_ivf_scan,
    auto_default="xla",
    dims=("n", "k", "d"),
    doc="IVF-Flat probe scan path (spatial/ann.py): xla = gather + "
        "einsum + select oracle, pallas = fused one-pass "
        "slot-streaming kernel, pallas_bf16 = bf16-multiplicand "
        "variant (f32 accumulate); unset = per-backend auto "
        "(currently xla everywhere until the TPU table lands)")

register(
    "ivf_pq_search", "pq_adc", ("gather", "onehot"),
    dims=("n", "k"),
    doc="IVF-PQ ADC lookup formulation (spatial/ann.py)")

register(
    "csr_spmv", "spmv_impl", ("segment", "cumsum", "sortscan"),
    no_sweep={"cumsum": ("differences a global running prefix — a "
                         "row's error scales with |cs| at its "
                         "position (sparse/linalg.py caveat); a "
                         "time-only sweep must not pick it")},
    dims=("rows", "nnz"),
    doc="CSR SpMV formulation (sparse/linalg.py)")

register(
    "mnmg_knn", "mnmg_merge", ("allgather", "ring", "hierarchical"),
    dims=("devices", "n", "k"),
    doc="cross-shard top-k merge topology (spatial/mnmg_knn.py + the "
        "sharded serve dispatch)")

register(
    "fused_l2_nn", "fused_nn_impl", ("xla", "pallas"),
    legality=lambda v, ctx: (_off_tpu_sweep(ctx) if v == "pallas"
                             else None),
    config_knob=False, default=None,
    dims=("n", "k"),
    doc="fused 1-NN path (distance/fused_l2_nn.py) — argument-only "
        "today (no config knob); unset = per-backend auto (pallas on "
        "TPU for the plain f32 min-reduce, xla otherwise)")

# registry-only knobs: validated here, NEVER resolved from config —
# the pin is the point (a process-wide configure() must not reach them)
register(
    "fused_knn_twophase", "merge_select_impl",
    ("topk", "approx", "approx95", "chunked", "pallas"),
    legality=_legal_select_impl,
    config_knob=False, default="topk",
    dims=("n", "k"),
    doc="phase-2 merge select of the two-phase fused kNN — pinned to "
        "exact 'topk' so a process-wide select_impl pin cannot trade "
        "the kernel's exactness contract away silently")

register(
    "mnmg_knn", "mnmg_group_size", None,
    legality=_legal_group_size,
    config_knob=False, default=None,
    dims=("devices",),
    doc="hierarchical-merge host-group size (free-form int; must "
        "divide the merge axis size)")
