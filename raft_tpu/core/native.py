"""ctypes bindings to the native host runtime (cpp/src/host_runtime.cpp).

The TPU analog of the reference's Cython layer (python/raft/common/*.pyx):
the C++ side exports a plain C ABI, and this module compiles (if needed),
loads, and wraps it.  Every wrapper has a pure-Python fallback, so the
package works without a toolchain; ``native_available()`` reports which
path is active.

Build strategy: look for a prebuilt ``libraft_tpu_host.so`` (cmake install
or earlier lazy build), else compile once with g++ into
``cpp/build/`` — a few hundred ms, cached across sessions.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CPP = os.path.join(_ROOT, "cpp")
_BUILD = os.path.join(_CPP, "build")
_SO = os.path.join(_BUILD, "libraft_tpu_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def lazy_build_so(so_path: str, src: str, deps: Optional[list] = None,
                  includes: Optional[list] = None,
                  libs: Optional[list] = None,
                  opt: str = "-O3") -> Optional[str]:
    """Build (if missing or stale vs ``deps``) and return the .so path.

    Shared by every native extension (host runtime, PJRT handle): one
    place owns the g++ invocation, the staleness rule, and the
    compile-to-per-pid-temp + atomic-rename step that keeps concurrent
    first-use processes from loading a half-written .so.  Returns None
    when the source is absent or the toolchain fails (callers degrade to
    their Python fallbacks).
    """
    if not os.path.exists(src):
        return None
    deps = [src] + list(deps or [])

    def stale() -> bool:
        try:
            so_mtime = os.path.getmtime(so_path)
            return any(so_mtime < os.path.getmtime(d) for d in deps
                       if os.path.exists(d))
        except OSError:
            return True

    if os.path.exists(so_path) and not stale():
        return so_path
    os.makedirs(os.path.dirname(so_path), exist_ok=True)
    tmp = f"{so_path}.{os.getpid()}.tmp"
    cmd = ["g++", opt, "-std=c++17", "-shared", "-fPIC"]
    for inc in includes or [os.path.join(_CPP, "include")]:
        cmd += ["-I", inc]
    cmd += [src, "-o", tmp] + list(libs or [])
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
        return so_path
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = lazy_build_so(_SO, os.path.join(_CPP, "src", "host_runtime.cpp"))
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            _bind(lib)
        except (OSError, AttributeError):
            # load failure or missing symbol (stale ABI) → Python fallback
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.rt_version.restype = ctypes.c_char_p
    lib.rt_alloc.restype = ctypes.c_void_p
    lib.rt_alloc.argtypes = [ctypes.c_size_t]
    lib.rt_free.argtypes = [ctypes.c_void_p]
    lib.rt_arena_total.restype = ctypes.c_size_t
    lib.rt_arena_in_use.restype = ctypes.c_size_t
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.rt_build_dendrogram.restype = ctypes.c_int
    lib.rt_build_dendrogram.argtypes = [
        i64p, i64p, f64p, ctypes.c_int64, i64p, f64p, i64p]
    lib.rt_extract_clusters.restype = ctypes.c_int
    lib.rt_extract_clusters.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int64, i64p]
    lib.rt_build_lists.restype = ctypes.c_int
    lib.rt_build_lists.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.rt_pack_groups.restype = ctypes.c_int
    lib.rt_pack_groups.argtypes = [
        i64p, f64p, ctypes.c_int64, ctypes.c_int64, i64p,
        ctypes.c_int64, f64p]


def native_available() -> bool:
    return _load() is not None


def native_version() -> Optional[str]:
    lib = _load()
    return lib.rt_version().decode() if lib else None


def arena_stats() -> Tuple[int, int]:
    """(total_bytes, in_use_bytes) of the native host arena (0, 0 if the
    native layer is unavailable)."""
    lib = _load()
    if lib is None:
        return (0, 0)
    return int(lib.rt_arena_total()), int(lib.rt_arena_in_use())


# --------------------------------------------------------------------- #
# wrapped algorithms (native with Python fallback)
# --------------------------------------------------------------------- #
def build_dendrogram(src, dst, weights, m: int
                     ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Native union-find dendrogram; None → caller should use the Python
    path (raft_tpu.sparse.hierarchy.build_dendrogram_host)."""
    lib = _load()
    if lib is None or m < 2:
        return None
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    w = np.ascontiguousarray(weights, np.float64)
    children = np.empty(2 * (m - 1), np.int64)
    delta = np.empty(m - 1, np.float64)
    sizes = np.empty(m - 1, np.int64)
    rc = lib.rt_build_dendrogram(src, dst, w, m, children, delta, sizes)
    if rc != 0:
        return None
    return children.reshape(m - 1, 2), delta, sizes


def extract_clusters(children, n_clusters: int, n_leaves: int
                     ) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    ch = np.ascontiguousarray(np.asarray(children).reshape(-1), np.int64)
    labels = np.empty(n_leaves, np.int64)
    rc = lib.rt_extract_clusters(ch, n_clusters, n_leaves, labels)
    return labels if rc == 0 else None


def build_lists(labels, nlist: int) -> Optional[Tuple[np.ndarray, int]]:
    """Native padded inverted-list packing; None → Python fallback."""
    lib = _load()
    if lib is None:
        return None
    lab = np.ascontiguousarray(labels, np.int64)
    m = len(lab)
    ml = ctypes.c_int64(0)
    if lib.rt_build_lists(lab, m, nlist, None, 0, ctypes.byref(ml)) != 0:
        return None
    max_len = max(int(ml.value), 1)
    table = np.empty(nlist * max_len, np.int64)
    rc = lib.rt_build_lists(
        lab, m, nlist, table.ctypes.data_as(ctypes.c_void_p), max_len, None)
    if rc != 0:
        return None
    return table.reshape(nlist, max_len), max_len


def pack_groups(owner, dist, L: int, gmax: int
                ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native ball-cover group packing; None → Python fallback."""
    lib = _load()
    if lib is None:
        return None
    o = np.ascontiguousarray(owner, np.int64)
    d = np.ascontiguousarray(dist, np.float64)
    groups = np.empty(L * gmax, np.int64)
    radius = np.empty(L, np.float64)
    rc = lib.rt_pack_groups(o, d, len(o), L, groups, gmax, radius)
    if rc != 0:
        return None
    return groups.reshape(L, gmax), radius
