"""Small host-side integer / power-of-two utilities.

TPU-native analog of the reference's device-side helpers that remain
meaningful on the host: ``ceildiv``/``alignTo``/``alignDown``/``isPo2``/
``log2`` (cpp/include/raft/cuda_utils.cuh:109-217), the ``Pow2`` arithmetic
helper (cpp/include/raft/pow2_utils.cuh) and ``integer_utils.h``.  Warp/lane
intrinsics have no host analog — their role is played by Pallas kernel tiling
(see raft_tpu/ops).
"""

from __future__ import annotations

from raft_tpu.core.error import expects


def is_tpu_backend() -> bool:
    """True when the default JAX backend is TPU hardware.

    The platform name is not always ``"tpu"``: tunneled/proxied PJRT
    plugins register under their own name (e.g. ``axon``) while still
    driving a real TPU and canonicalizing to the ``tpu`` lowering path,
    so checking ``jax.default_backend() == "tpu"`` alone would silently
    route hot paths (compiled Pallas kernels) to their interpret/XLA
    fallbacks on exactly the hardware they were built for.
    """
    import jax

    if jax.default_backend() == "tpu":
        return True
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return "tpu" in (getattr(dev, "device_kind", "") or "").lower()


def ceildiv(a: int, b: int) -> int:
    """Ceiling division (reference cuda_utils.cuh:109 ``raft::ceildiv``)."""
    return -(-a // b)


def round_up_safe(a: int, b: int) -> int:
    """Round ``a`` up to a multiple of ``b`` (integer_utils.h)."""
    return ceildiv(a, b) * b


def round_down_safe(a: int, b: int) -> int:
    """Round ``a`` down to a multiple of ``b`` (integer_utils.h)."""
    return (a // b) * b


def align_to(v: int, align: int) -> int:
    """Align ``v`` up to ``align`` (reference cuda_utils.cuh ``alignTo``)."""
    return round_up_safe(v, align)


def align_down(v: int, align: int) -> int:
    """Align ``v`` down to ``align`` (reference cuda_utils.cuh ``alignDown``)."""
    return round_down_safe(v, align)


def is_pow2(v: int) -> bool:
    """True iff ``v`` is a power of two (reference cuda_utils.cuh ``isPo2``)."""
    return v > 0 and (v & (v - 1)) == 0


def log2(v: int) -> int:
    """Floor log base 2 (reference cuda_utils.cuh ``log2``)."""
    expects(v > 0, "log2: v must be positive, got %d", v)
    return v.bit_length() - 1


class Pow2:
    """Fast arithmetic modulo a power of two (reference pow2_utils.cuh).

    Provides div/mod/round up/round down and alignment predicates for a
    compile-time-style power-of-two value.
    """

    def __init__(self, value: int):
        expects(is_pow2(value), "Pow2: value must be a power of two, got %d", value)
        self.value = value
        self.mask = value - 1
        self.log2 = log2(value)

    def div(self, x: int) -> int:
        return x >> self.log2

    def mod(self, x: int) -> int:
        return x & self.mask

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0


def as_pytree_fn(fn):
    """Normalize a callable so it can cross a ``jax.jit`` boundary as an
    ARGUMENT (``jax.tree_util.Partial``): bound methods of
    pytree-registered objects rebind through the class function so the
    instance flows as a traced pytree (executable cache keys on
    structure + shapes, arrays are operands, not embedded constants).
    Plain functions become leafless Partials — static under jit, cached
    by function identity; a fresh closure per call still retraces, so
    hot paths should pass stable function objects (module-level
    functions, ``functools.lru_cache``-memoized factories, or
    Partials over array args)."""
    import jax
    from jax.tree_util import Partial

    if isinstance(fn, Partial):
        return fn
    self_ = getattr(fn, "__self__", None)
    if self_ is not None and not jax.tree_util.all_leaves([self_]):
        return Partial(fn.__func__, self_)
    return Partial(fn)
