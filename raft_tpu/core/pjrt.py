"""ctypes binding to the C++ PJRT handle (cpp/src/pjrt_handle.cpp).

The C++-consumable layer of SURVEY §7 step 1: ``raft_tpu::pjrt::Handle``
plays the role reference ``raft::handle_t`` (cpp/include/raft/handle.hpp:49)
plays for C++ consumers — it owns the device runtime (a PJRT plugin)
behind a stable C ABI.  This module compiles/loads the library lazily
and exposes the two probes; like :mod:`raft_tpu.core.native`, absence of
a toolchain degrades gracefully (``pjrt_native_available() -> False``).
"""

from __future__ import annotations

import ctypes
import glob
import json
import os
import threading
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CPP = os.path.join(_ROOT, "cpp")
_BUILD = os.path.join(_CPP, "build")
_SO = os.path.join(_BUILD, "libraft_tpu_pjrt.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    from raft_tpu.core.native import lazy_build_so

    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = lazy_build_so(
            _SO, os.path.join(_CPP, "src", "pjrt_handle.cpp"),
            deps=[
                os.path.join(_CPP, "include", "raft_tpu", "pjrt_handle.hpp"),
                os.path.join(_CPP, "third_party", "xla", "pjrt", "c",
                             "pjrt_c_api.h"),
            ],
            includes=[os.path.join(_CPP, "include"),
                      os.path.join(_CPP, "third_party")],
            libs=["-ldl"], opt="-O2")
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            for fn in ("raft_tpu_pjrt_probe", "raft_tpu_pjrt_client_info"):
                getattr(lib, fn).restype = ctypes.c_int
                getattr(lib, fn).argtypes = [
                    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        except (OSError, AttributeError):
            return None
        _lib = lib
        return _lib


def pjrt_native_available() -> bool:
    return _load() is not None


_plugin_path_cache: Optional[str] = None
_plugin_path_searched = False


def default_plugin_path() -> Optional[str]:
    """Locate a PJRT plugin .so: RAFT_TPU_PJRT_PLUGIN env wins, else the
    installed libtpu.  The filesystem fallback search is cached — the
    recursive globs can take seconds on hosts with a large /opt, exactly
    the machines where the fallback runs."""
    global _plugin_path_cache, _plugin_path_searched
    env = os.environ.get("RAFT_TPU_PJRT_PLUGIN")
    if env:
        return env
    if _plugin_path_searched:
        return _plugin_path_cache
    try:
        import libtpu

        path = os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so")
    except ImportError:
        path = None
    if path is None:
        for pattern in ("/usr/lib/**/libtpu.so", "/opt/**/libtpu/libtpu.so"):
            hits = glob.glob(pattern, recursive=True)
            if hits:
                path = hits[0]
                break
    _plugin_path_cache = path
    _plugin_path_searched = True
    return path


def _call(fn_name: str, plugin_path: str) -> dict:
    lib = _load()
    if lib is None:
        raise RuntimeError("native PJRT layer unavailable (no toolchain?)")
    buf = ctypes.create_string_buffer(1 << 20)
    rc = getattr(lib, fn_name)(plugin_path.encode(), buf, len(buf))
    text = buf.value.decode(errors="replace")
    if rc != 0:
        raise RuntimeError(text)
    return json.loads(text)


def probe_api_version(plugin_path: Optional[str] = None) -> dict:
    """{"api_version": [major, minor]} of the plugin — dlopen +
    GetPjrtApi + Plugin_Initialize only; never touches devices."""
    path = plugin_path or default_plugin_path()
    if path is None:
        raise RuntimeError("no PJRT plugin found (set RAFT_TPU_PJRT_PLUGIN)")
    return _call("raft_tpu_pjrt_probe", path)


def client_info(plugin_path: Optional[str] = None) -> dict:
    """Full client bring-up: {"platform", "version", "devices": [...]}.
    Expensive, device-touching; raises with the plugin's message when the
    process has no device access."""
    path = plugin_path or default_plugin_path()
    if path is None:
        raise RuntimeError("no PJRT plugin found (set RAFT_TPU_PJRT_PLUGIN)")
    return _call("raft_tpu_pjrt_client_info", path)
