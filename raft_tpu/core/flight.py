"""Flight recorder: always-on bounded event capture + request tracing.

The serving stack's aggregate metrics (docs/OBSERVABILITY.md) answer
"how is the fleet doing"; this module answers "what happened to THIS
request" and "what were the seconds before the outage".  Three pieces,
all in-process, all bounded, all cheap enough to leave on in
production (the ``serve_trace_overhead`` bench rung measures the cost
and asserts it ≤ 3% qps):

**FlightRecorder** — a lock-cheap ring buffer of typed structured
events (``ts, kind, service, tenant, trace_id, attrs``).  Every layer
of the serve pipeline records into one process-global ordered stream:
request lifecycle events (admitted → batch_formed → execute_launch →
execute_ready → resolved/expired/failed/requeued) *and* system events
(breaker transitions, recovery phases, repartitions, compactions,
hot-set promotions, worker restarts, tile-miss storms), so the stream
reads like a black box's tape — what the system did, in order.

**Request-scoped traces** — ``Service.submit`` assigns each admitted
request a process-unique ``trace_id`` and a :class:`Trace`; every
event recorded against the request lands BOTH in the global ring and
in the trace's own bounded list, so
:meth:`~raft_tpu.serve.batcher.ServeFuture.trace` reconstructs the
complete per-request timeline after resolution even if the global
ring has since wrapped.  Batch-level events (the batch a request rode,
its bucket rung, the execute bracket, hedge arms/winner) attach to
every rider's trace via :func:`batch_scope` — the worker wraps the
device call in the scope and deeper layers (replica hedging) record
through :func:`record_scoped` without threading trace handles through
their signatures.

**Black-box dumps** — :meth:`FlightRecorder.blackbox` snapshots the
last N events under a reason; breaker trips and recoveries call it
automatically, so a chaos postmortem starts from the tape, not from
grepping logs.  Snapshots are kept in a bounded deque (and written as
JSON files when ``RAFT_TPU_FLIGHT_DUMP_DIR`` names a directory);
session ``health_check()`` and ``metrics_snapshot()`` surface them.

**SLO tracking + exemplars** — :class:`SLOTracker` (one per service,
fed per resolved/expired request) tracks a per-tenant latency target
and deadline-hit-rate with multi-window burn rates
(``burn = miss_rate / (1 - objective)``; > 1 means the error budget
is burning faster than it accrues), published as
``raft_tpu_serve_slo_*`` gauges and in ``Service.stats()``.
:class:`Exemplars` keeps the trace_ids of the slowest K observations
per service, so a p99 number links to the timelines that produced it.

``RAFT_TPU_FLIGHT=0`` (or :func:`set_enabled`) turns the whole
subsystem into a no-op: ``new_trace`` returns None, ``record`` returns
immediately, SLO/exemplar observation is skipped — the
``serve_trace_overhead`` rung's baseline arm.  Event kinds and the
trace_id contract are documented in docs/OBSERVABILITY.md ("Flight
recorder & request tracing").
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu.core import metrics as _metrics

__all__ = [
    "Event", "Trace", "FlightRecorder", "SLOTracker", "Exemplars",
    "TERMINAL_KINDS", "default_recorder", "record", "record_scoped",
    "batch_scope", "trace_context", "current_trace_context",
    "fleet_traces", "set_enabled", "is_enabled", "slo_for",
    "exemplars_for", "slo_snapshot", "exemplars_snapshot",
    "flight_snapshot", "reset",
]

_enabled = os.environ.get("RAFT_TPU_FLIGHT", "1") != "0"

# a request's lifecycle ends with exactly ONE of these (the invariant
# tests/test_flight.py asserts across every path)
TERMINAL_KINDS = frozenset(("resolved", "expired", "failed"))

# per-trace event cap: a single request's timeline is short by
# construction (admitted + batch + bracket + terminal, plus hedge /
# requeue noise); the cap only guards against a pathological producer
TRACE_MAX_EVENTS = 256

# black-box snapshots retained in memory (each is a bounded event list)
BLACKBOX_KEEP = 8

# distinct fleet trace ids whose local Trace objects the recorder
# indexes (FIFO-evicted).  Each entry holds at most a handful of
# traces (one per RPC attempt that landed here), so the bound is the
# memory contract for the fleet join path the same way ``capacity``
# is for the ring.
FLEET_TRACE_KEEP = 512

# local traces retained per fleet id (retries/hedges to the same
# process each open a fresh local trace under the same fleet id)
FLEET_TRACES_PER_ID = 8


def set_enabled(on: bool) -> None:
    """Globally enable/disable flight recording (RAFT_TPU_FLIGHT=0).
    Disabled: no events, no traces, no SLO/exemplar observation."""
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


class Event:
    """One structured flight event (immutable by convention)."""

    __slots__ = ("ts", "kind", "service", "tenant", "trace_id", "attrs")

    def __init__(self, ts: float, kind: str, service: Optional[str],
                 tenant: Optional[str], trace_id: Optional[int],
                 attrs: Optional[dict]):
        self.ts = ts
        self.kind = kind
        self.service = service
        self.tenant = tenant
        self.trace_id = trace_id
        self.attrs = attrs

    def to_dict(self) -> dict:
        out = {"ts": self.ts, "kind": self.kind}
        if self.service is not None:
            out["service"] = self.service
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.attrs:
            out.update(self.attrs)
        return out

    def __repr__(self) -> str:  # debugging aid only
        return "Event(%r, t=%.6f, trace=%r)" % (self.kind, self.ts,
                                                self.trace_id)


class Trace:
    """One request's private timeline (the half of tracing that
    survives ring wrap-around).  ``trace_id`` is a process-unique
    monotonically increasing int — two requests never share one, and
    a larger id was admitted later.  Event appends are list-append
    atomic under the GIL; the producers are already sequenced by the
    request lifecycle (submit → worker → resolve)."""

    __slots__ = ("trace_id", "service", "tenant", "events", "dropped",
                 "fleet")

    def __init__(self, trace_id: int, service: Optional[str],
                 tenant: Optional[str]):
        self.trace_id = trace_id
        self.service = service
        self.tenant = tenant
        self.events: List[Event] = []
        self.dropped = 0
        # fleet trace context this request rides under (propagated by
        # the router: {"id", "parent", "sent_at"}), or None for a
        # plain in-process request — see docs/OBSERVABILITY.md
        # "Fleet tracing"
        self.fleet: Optional[dict] = None

    def add(self, ev: Event) -> None:
        if len(self.events) >= TRACE_MAX_EVENTS:
            self.dropped += 1
            return
        self.events.append(ev)

    def timeline(self) -> List[dict]:
        """The ordered event dicts — the ``ServeFuture.trace()``
        payload ``tools/trace_report.py`` renders."""
        return [ev.to_dict() for ev in list(self.events)]

    def kinds(self) -> List[str]:
        return [ev.kind for ev in list(self.events)]

    def terminal(self) -> Optional[str]:
        """The terminal kind (resolved/expired/failed), or None while
        the request is still in flight."""
        for ev in reversed(list(self.events)):
            if ev.kind in TERMINAL_KINDS:
                return ev.kind
        return None

    def duration_s(self) -> Optional[float]:
        evs = list(self.events)
        if len(evs) < 2:
            return None
        return evs[-1].ts - evs[0].ts

    def to_dict(self) -> dict:
        out = {"trace_id": self.trace_id, "service": self.service,
               "tenant": self.tenant, "terminal": self.terminal(),
               "dropped": self.dropped, "events": self.timeline()}
        if self.fleet is not None:
            out["fleet"] = dict(self.fleet)
        return out


# -- batch scope: the worker binds the current batch's rider traces to
# its thread so deeper layers (replica hedging) can attach events
# without signature plumbing ------------------------------------------ #
_tls = threading.local()


@contextlib.contextmanager
def batch_scope(traces: Sequence[Optional[Trace]]):
    """Bind ``traces`` as the calling thread's current batch riders for
    the duration of the block (:func:`record_scoped` attaches to
    them).  Nestable; None entries (disabled recording) are skipped."""
    prev = getattr(_tls, "scope", None)
    _tls.scope = tuple(t for t in traces if t is not None)
    try:
        yield
    finally:
        _tls.scope = prev


def _scope_traces() -> Tuple[Trace, ...]:
    return getattr(_tls, "scope", None) or ()


@contextlib.contextmanager
def trace_context(ctx: Optional[dict]):
    """Bind a propagated fleet trace context (``{"id", "parent",
    "sent_at"}``) to the calling thread: every :meth:`new_trace` created
    inside the block is stamped with it and indexed by fleet id, so a
    worker process can later serve its half of the cross-process
    waterfall (docs/OBSERVABILITY.md "Fleet tracing").  ``ctx=None``
    is a no-op block, so callers can pass through whatever the wire
    carried without branching."""
    prev = getattr(_tls, "fleet_ctx", None)
    _tls.fleet_ctx = dict(ctx) if ctx else None
    try:
        yield
    finally:
        _tls.fleet_ctx = prev


def current_trace_context() -> Optional[dict]:
    """The calling thread's propagated fleet trace context, if any."""
    return getattr(_tls, "fleet_ctx", None)


class FlightRecorder:
    """Bounded, thread-safe, ordered event ring (module doc).

    Parameters
    ----------
    capacity:
        Ring size in events; None resolves the ``flight_events`` knob
        (:mod:`raft_tpu.config`).  The bound is the memory contract:
        the recorder can never hold more than ``capacity`` events
        however long the process runs.
    clock:
        Monotonic-seconds source (the library's injectable-clock seam;
        event ``ts`` values are this clock's seconds).
    """

    def __init__(self, capacity: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        if capacity is None:
            from raft_tpu import config

            capacity = config.get_int("flight_events")
        if capacity < 1:
            raise ValueError("FlightRecorder: capacity=%d" % capacity)
        self._lock = threading.Lock()
        self._ring: "collections.deque[Event]" = collections.deque(
            maxlen=int(capacity))
        self._blackboxes: "collections.deque[dict]" = collections.deque(
            maxlen=BLACKBOX_KEEP)
        self._trace_seq = itertools.count(1)
        self._clock = clock
        self._dump_seq = itertools.count(1)
        # fleet id -> local Trace objects created under that context
        # (insertion-ordered; FIFO-evicted at FLEET_TRACE_KEEP ids).
        # This is what lets the worker answer /debug/trace for a fleet
        # id even after the global ring has wrapped.
        self._fleet: Dict[str, List[Trace]] = {}

    # ------------------------------------------------------------------ #
    # producers
    # ------------------------------------------------------------------ #
    def new_trace(self, service: Optional[str] = None,
                  tenant: Optional[str] = None, *,
                  fleet: Optional[dict] = None) -> Optional[Trace]:
        """A fresh request trace with a process-unique id, or None when
        recording is disabled (callers treat a None trace as 'no
        tracing' everywhere).  ``fleet`` (or, when absent, the calling
        thread's :func:`trace_context`) stamps the trace with a
        propagated fleet context and indexes it by fleet id for the
        cross-process join."""
        if not _enabled:
            return None
        tr = Trace(next(self._trace_seq), service, tenant)
        ctx = fleet if fleet is not None else current_trace_context()
        if ctx and ctx.get("id") is not None:
            tr.fleet = dict(ctx)
            self._index_fleet(tr)
        return tr

    def _index_fleet(self, trace: Trace) -> None:
        fid = str(trace.fleet["id"])  # type: ignore[index]
        with self._lock:
            lst = self._fleet.get(fid)
            if lst is None:
                while len(self._fleet) >= FLEET_TRACE_KEEP:
                    self._fleet.pop(next(iter(self._fleet)))
                lst = self._fleet[fid] = []
            if len(lst) < FLEET_TRACES_PER_ID:
                lst.append(trace)

    def record(self, kind: str, service: Optional[str] = None,
               tenant: Optional[str] = None,
               trace: Optional[Trace] = None,
               traces: Optional[Sequence[Optional[Trace]]] = None,
               **attrs: Any) -> Optional[Event]:
        """Record one event into the ring and onto the given trace(s).

        ``trace`` attaches to one request, ``traces`` to every rider of
        a batch (None entries skipped).  System events pass neither.
        Returns the event (None when disabled).
        """
        if not _enabled:
            return None
        if tenant is None and trace is not None:
            tenant = trace.tenant
        ring_attrs = attrs or None
        riders = ([t for t in traces if t is not None]
                  if traces else ())
        if riders:
            # the shared ring event names every rider, so a ring dump
            # alone (black box, trace-dump file) can reconstruct each
            # request's batch-level steps after the Trace objects are
            # gone (tools/trace_report.py reads `traces`)
            ring_attrs = dict(attrs or {},
                              traces=[t.trace_id for t in riders])
            fids = sorted({str(t.fleet["id"]) for t in riders
                           if t.fleet is not None
                           and t.fleet.get("id") is not None})
            if fids:
                ring_attrs["fleet"] = fids
        elif trace is not None and trace.fleet is not None:
            fid = trace.fleet.get("id")
            if fid is not None:
                ring_attrs = dict(attrs or {}, fleet=str(fid))
        ev = Event(self._clock(), kind, service, tenant,
                   trace.trace_id if trace is not None else None,
                   ring_attrs)
        with self._lock:
            self._ring.append(ev)
        if trace is not None:
            trace.add(ev)
        for t in riders:
            # per-rider view of a shared event: same ts/kind/attrs,
            # the rider's own trace_id
            t.add(Event(ev.ts, kind, service, t.tenant, t.trace_id,
                        attrs or None))
        return ev

    def record_scoped(self, kind: str, service: Optional[str] = None,
                      **attrs: Any) -> Optional[Event]:
        """Record one event attached to the calling thread's current
        :func:`batch_scope` riders (no-op scope = ring-only)."""
        return self.record(kind, service=service,
                           traces=_scope_traces(), **attrs)

    # ------------------------------------------------------------------ #
    # consumers
    # ------------------------------------------------------------------ #
    def events(self, last: Optional[int] = None,
               service: Optional[str] = None,
               kind: Optional[str] = None) -> List[Event]:
        """A filtered copy of the ring (oldest first)."""
        with self._lock:
            evs = list(self._ring)
        if service is not None:
            evs = [e for e in evs if e.service == service]
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        if last is not None:
            evs = evs[-int(last):]
        return evs

    def fleet_traces(self, fleet_id: str) -> List[Trace]:
        """The local Trace objects created under the given fleet trace
        context (empty when unknown or evicted) — the worker's half of
        ``/fleet/debug/trace/<id>``.  Survives ring wrap: the Trace
        keeps its own bounded event list."""
        with self._lock:
            return list(self._fleet.get(str(fleet_id), ()))

    def fleet_trace_ids(self) -> List[str]:
        """Indexed fleet ids, oldest first."""
        with self._lock:
            return list(self._fleet)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    # ------------------------------------------------------------------ #
    # black box
    # ------------------------------------------------------------------ #
    def blackbox(self, reason: str, service: Optional[str] = None,
                 last: int = 256) -> dict:
        """Snapshot the last ``last`` ring events under ``reason`` —
        the postmortem tape a breaker trip / recovery captures
        automatically.  Kept in a bounded deque (``blackboxes()``);
        written as a JSON file too when ``RAFT_TPU_FLIGHT_DUMP_DIR``
        names a directory.  Safe to call with recording disabled
        (snapshots whatever the ring still holds)."""
        with self._lock:
            evs = list(self._ring)[-int(last):]
        dump = {"reason": reason, "service": service,
                "at": self._clock(),
                "events": [e.to_dict() for e in evs]}
        with self._lock:
            self._blackboxes.append(dump)
        _metrics.default_registry().counter(
            "raft_tpu_flight_blackboxes_total",
            help="black-box event-buffer snapshots captured "
                 "(breaker trips, recoveries, manual dumps)").inc()
        dump_dir = os.environ.get("RAFT_TPU_FLIGHT_DUMP_DIR")
        if dump_dir:
            try:
                path = os.path.join(
                    dump_dir, "flight_%s_%d.json"
                    % (reason, next(self._dump_seq)))
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(dump, f, indent=2, sort_keys=True)
                    f.write("\n")
            except OSError:
                pass  # a broken dump dir must never take serving down
        return dump

    def blackboxes(self) -> List[dict]:
        with self._lock:
            return list(self._blackboxes)

    def blackbox_summaries(self) -> List[dict]:
        """Header-only view (``health_check`` embeds this — the full
        event payload stays in :meth:`blackboxes` / the dump files)."""
        return [{"reason": b["reason"], "service": b["service"],
                 "at": b["at"], "n_events": len(b["events"])}
                for b in self.blackboxes()]

    def dump_to(self, path: str) -> dict:
        """Write the whole recorder state (ring + black boxes) as JSON
        — the chaos harness's on-failure dump."""
        with self._lock:
            state = {"capacity": self.capacity,
                     "events": [e.to_dict() for e in self._ring],
                     "blackboxes": list(self._blackboxes)}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(state, f, indent=2, sort_keys=True)
            f.write("\n")
        return state

    def clear(self) -> None:
        """Drop every event, black box and fleet index entry (test
        isolation)."""
        with self._lock:
            self._ring.clear()
            self._blackboxes.clear()
            self._fleet.clear()


# ---------------------------------------------------------------------- #
# SLO tracking (per service, per tenant)
# ---------------------------------------------------------------------- #
class SLOTracker:
    """Per-tenant latency-target / deadline-hit-rate tracker with
    multi-window burn rates (module doc).

    Parameters
    ----------
    service:
        Metric label; one tracker per service.
    target_s:
        The latency objective per request; <= 0 means "deadline-only"
        (a request without a deadline is then always a hit).
    objective:
        The availability objective in (0, 1) — e.g. 0.99 means 1% of
        requests may miss before the error budget is spent.  Burn rate
        over a window = observed miss rate / (1 - objective); burn 1.0
        spends the budget exactly as fast as it accrues.
    windows_s:
        The burn-rate windows in seconds (multi-window alerting: a
        short window catches a fast burn, a long one a slow leak).
    clock:
        Shared with the owning service (deterministic tests drive it).
    """

    MAX_OUTCOMES = 4096   # per tenant: (ts, ok) pairs retained

    def __init__(self, service: str, target_s: float, objective: float,
                 windows_s: Sequence[float],
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < objective < 1.0:
            raise ValueError("SLOTracker: objective=%r" % objective)
        self.service = service
        self.target_s = float(target_s)
        self.objective = float(objective)
        self.windows_s = tuple(float(w) for w in windows_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: Dict[str, collections.deque] = {}

    def clear(self) -> None:
        """Drop every recorded outcome (test isolation via
        :func:`reset`; the tracker object — and every cached reference
        to it — stays valid)."""
        with self._lock:
            self._outcomes.clear()

    def observe(self, tenant: Optional[str], latency_s: float,
                deadline_ok: bool = True) -> bool:
        """Record one finished request; returns whether it was an SLO
        hit.  A miss is a blown deadline, a failure (callers pass
        ``deadline_ok=False``), or latency over the target."""
        if not _enabled:
            return True
        ok = deadline_ok and (self.target_s <= 0.0
                              or latency_s <= self.target_s)
        tenant = tenant or "default"
        with self._lock:
            dq = self._outcomes.get(tenant)
            if dq is None:
                dq = self._outcomes[tenant] = collections.deque(
                    maxlen=self.MAX_OUTCOMES)
            dq.append((self._clock(), ok))
        if not ok:
            _metrics.default_registry().counter(
                "raft_tpu_serve_slo_misses_total",
                help="requests that missed the service's SLO (latency "
                     "target or deadline), per tenant",
                labels=("service", "tenant")).labels(
                    service=self.service, tenant=tenant).inc()
        return ok

    def snapshot(self, publish: bool = True) -> dict:
        """Per-tenant SLO state: totals, hit ratio, and the burn rate
        per configured window; publishes the gauges as a side effect
        (``publish=False`` for read-only callers)."""
        now = self._clock()
        with self._lock:
            per_tenant = {t: list(dq)
                          for t, dq in self._outcomes.items()}
        budget = 1.0 - self.objective
        out: dict = {"target_ms": self.target_s * 1e3,
                     "objective": self.objective,
                     "windows_s": list(self.windows_s), "tenants": {}}
        reg = _metrics.default_registry()
        for tenant, outcomes in sorted(per_tenant.items()):
            total = len(outcomes)
            misses = sum(1 for _, ok in outcomes if not ok)
            hit_ratio = (total - misses) / total if total else 1.0
            burns = {}
            for w in self.windows_s:
                in_w = [ok for ts, ok in outcomes if now - ts <= w]
                rate = (sum(1 for ok in in_w if not ok) / len(in_w)
                        if in_w else 0.0)
                burns["%gs" % w] = rate / budget
            # the retained-outcome bound (MAX_OUTCOMES) can truncate a
            # long window at high rates: coverage_s is how far back
            # the retained history actually reaches — a burn over a
            # window longer than this is a partial-window number, and
            # the snapshot must say so rather than imply full coverage
            coverage_s = (now - outcomes[0][0]) if outcomes else 0.0
            out["tenants"][tenant] = {
                "total": total, "misses": misses,
                "hit_ratio": round(hit_ratio, 6),
                "coverage_s": round(coverage_s, 3),
                "burn": {k: round(v, 4) for k, v in burns.items()},
            }
            if publish:
                reg.gauge(
                    "raft_tpu_serve_slo_hit_ratio",
                    help="fraction of recent requests meeting the SLO "
                         "(latency target + deadline), per tenant",
                    labels=("service", "tenant")).labels(
                        service=self.service, tenant=tenant).set(
                            hit_ratio)
                for wname, burn in burns.items():
                    reg.gauge(
                        "raft_tpu_serve_slo_burn_rate",
                        help="error-budget burn rate per window "
                             "(miss_rate / (1 - objective); > 1 burns "
                             "budget faster than it accrues)",
                        labels=("service", "tenant", "window")).labels(
                            service=self.service, tenant=tenant,
                            window=wname).set(burn)
        return out


class Exemplars:
    """The slowest-K (latency, trace_id) observations per service —
    the bridge from a p99 number to the timelines behind it."""

    def __init__(self, k: int = 8):
        self._k = int(k)
        self._lock = threading.Lock()
        # min-heap-by-latency semantics via a sorted list (k is tiny)
        self._worst: List[Tuple[float, int]] = []

    def clear(self) -> None:
        """Drop the reservoir (test isolation via :func:`reset`; the
        object — and every cached reference — stays valid)."""
        with self._lock:
            self._worst.clear()

    def observe(self, latency_s: float, trace_id: Optional[int]) -> None:
        if not _enabled or trace_id is None:
            return
        with self._lock:
            if (len(self._worst) < self._k
                    or latency_s > self._worst[0][0]):
                self._worst.append((float(latency_s), int(trace_id)))
                self._worst.sort()
                del self._worst[:-self._k]

    def snapshot(self) -> List[dict]:
        """Slowest first."""
        with self._lock:
            worst = list(self._worst)
        return [{"latency_ms": round(lat * 1e3, 3), "trace_id": tid}
                for lat, tid in sorted(worst, reverse=True)]


# ---------------------------------------------------------------------- #
# module-level singletons and registries
# ---------------------------------------------------------------------- #
_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()
_slo: Dict[str, SLOTracker] = {}
_exemplars: Dict[str, Exemplars] = {}


def default_recorder() -> FlightRecorder:
    """The process-wide recorder every raft_tpu layer records into
    (lazily constructed so the ``flight_events`` knob is honored)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = FlightRecorder()
    return _default


def record(kind: str, **kwargs: Any) -> Optional[Event]:
    """``default_recorder().record(...)`` convenience."""
    if not _enabled:
        return None
    return default_recorder().record(kind, **kwargs)


def record_scoped(kind: str, **kwargs: Any) -> Optional[Event]:
    """``default_recorder().record_scoped(...)`` convenience."""
    if not _enabled:
        return None
    return default_recorder().record_scoped(kind, **kwargs)


def fleet_traces(fleet_id: str) -> List[Trace]:
    """``default_recorder().fleet_traces(...)`` convenience."""
    return default_recorder().fleet_traces(fleet_id)


def slo_for(service: str, target_s: float, objective: float,
            windows_s: Sequence[float],
            clock: Callable[[], float] = time.monotonic) -> SLOTracker:
    """Create-and-register the service's SLO tracker (latest
    registration wins — services are rebuilt freely in tests)."""
    tracker = SLOTracker(service, target_s, objective, windows_s,
                         clock=clock)
    with _default_lock:
        _slo[service] = tracker
    return tracker


def exemplars_for(service: str) -> Exemplars:
    """Get-or-create the service's slowest-K exemplar reservoir."""
    with _default_lock:
        ex = _exemplars.get(service)
        if ex is None:
            ex = _exemplars[service] = Exemplars()
        return ex


def slo_snapshot() -> Dict[str, dict]:
    with _default_lock:
        trackers = dict(_slo)
    return {name: t.snapshot() for name, t in sorted(trackers.items())}


def exemplars_snapshot() -> Dict[str, List[dict]]:
    with _default_lock:
        items = dict(_exemplars)
    snaps = {name: ex.snapshot() for name, ex in sorted(items.items())}
    return {name: snap for name, snap in snaps.items() if snap}


def flight_snapshot() -> dict:
    """The ``flight`` section of ``metrics_snapshot()`` — recorder
    occupancy, black-box headers, per-service SLO state, and the
    slowest-observation exemplars."""
    rec = default_recorder()
    return {
        "enabled": _enabled,
        "events": len(rec),
        "capacity": rec.capacity,
        "blackboxes": rec.blackbox_summaries(),
        "slo": slo_snapshot(),
        "exemplars": exemplars_snapshot(),
    }


def reset() -> None:
    """Drop all recorded state — the ring, black boxes, every SLO
    tracker's outcomes and every exemplar reservoir — for test
    isolation.  Objects are cleared IN PLACE and registrations are
    kept, so references cached by live services and workers (a
    ``ServeWorker``'s exemplar reservoir, a ``Service``'s SLO tracker)
    keep feeding the same objects the snapshots read — a reset must
    never silently orphan a live producer."""
    with _default_lock:
        for tracker in _slo.values():
            tracker.clear()
        for ex in _exemplars.values():
            ex.clear()
        rec = _default
    if rec is not None:
        rec.clear()
