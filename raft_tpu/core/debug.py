"""Numeric sanitizer hooks: the TPU analog of the reference's debug aids.

The reference's only sanitizer integration is the ``CUDA_ENABLE_LINEINFO``
CMake option, "useful for cuda-memcheck" (cpp/CMakeLists.txt:45) — memory
tools exist outside the library and are merely enabled by a build flag.
The failure mode that actually bites numeric primitives is silent
NaN/Inf propagation through iterative solvers, so the TPU build wires
the JAX-native equivalents (SURVEY.md §5: ``debug_nans`` / checkify)
as opt-in hooks on the solver paths (Lanczos, k-means):

- :func:`enable_debug_checks` / env ``RAFT_TPU_DEBUG=1`` turn on eager
  finiteness assertions (:func:`check_finite`) at solver entry and exit.
  They synchronize the device (like ``cuda-memcheck``, you pay for the
  diagnosis), which is why they are opt-in.
- :func:`debug_nans` scopes JAX's own ``jax_debug_nans`` — every jitted
  computation under it re-runs un-jitted on NaN production and raises at
  the producing primitive.
- :func:`checkify_checks` wraps a jittable function with
  ``jax.experimental.checkify`` so float checks run *inside* the
  compiled program (no host sync per call) and surface as errors after.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Callable

import jax
import jax.numpy as jnp

from raft_tpu.core.error import RaftError

_enabled = os.environ.get("RAFT_TPU_DEBUG", "") == "1"


class NumericError(RaftError):
    """A debug-mode finiteness check failed (non-finite values where a
    solver requires finite data)."""


def enable_debug_checks(on: bool = True) -> None:
    """Globally enable/disable the eager finiteness checks."""
    global _enabled
    _enabled = bool(on)


def debug_checks_enabled() -> bool:
    return _enabled


def check_finite(x, name: str):
    """If debug checks are on: block on ``x`` and raise
    :class:`NumericError` when it contains NaN/Inf.  Returns ``x`` either
    way so it can be used inline at solver boundaries.

    This is an *eager* sanitizer: under an outer ``jax.jit`` trace the
    value is abstract and cannot be inspected, so the check is skipped
    there (in-trace checking is :func:`checkify_checks`'s job — wrap the
    jitted pipeline instead)."""
    if _enabled and not isinstance(x, jax.core.Tracer):
        ok = bool(jnp.all(jnp.isfinite(x)))
        if not ok:
            raise NumericError(
                f"debug check failed: '{name}' contains non-finite values "
                f"(shape {tuple(x.shape)}, dtype {x.dtype})")
    return x


@contextmanager
def debug_nans(enable: bool = True):
    """Scope JAX's ``jax_debug_nans`` flag (SURVEY §5's named hook):
    inside the scope, any jitted op producing a NaN raises
    FloatingPointError at the producing primitive."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def checkify_checks(fn: Callable) -> Callable:
    """Wrap a jittable ``fn`` with checkify float checks compiled into
    the program: the returned function raises ``JaxRuntimeError``-style
    checkify errors (via ``error.throw()``) when a NaN/Inf is produced,
    without per-op host syncs."""
    from jax.experimental import checkify

    checked = checkify.checkify(fn, errors=checkify.float_checks)

    def wrapper(*args, **kw):
        err, out = checked(*args, **kw)
        err.throw()
        return out

    return wrapper
