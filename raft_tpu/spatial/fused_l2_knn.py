"""Fused L2 distance + k-nearest-neighbor selection.

Reference: ``fusedL2Knn`` (cpp/include/raft/spatial/knn/detail/
fused_l2_knn.cuh:196,946) — one CUDA kernel computes an L2 distance tile
and immediately runs a warp-select top-k over it, dumping intermediate
top-ks to shared memory and merging across tiles (the usePrevTopKs path),
so the (n_queries, n_index) distance matrix never exists in memory.
It is the fast path of ``brute_force_knn`` for k ≤ 64 / L2 / row-major
(detail/knn_brute_force_faiss.cuh:297-313).

TPU re-design: a ``lax.scan`` over index-row tiles.  Each step is one MXU
matmul (expanded ``xn + yn − 2·q@yᵀ`` form) followed by a tile-local
top-k, merged into the running (k,) result by concatenation + re-selection
— the reference's smem-merge becomes a (k + k)-wide top-k on registers,
and XLA pipelines the scan so the matmul of tile t+1 overlaps the
selection of tile t.  High-water memory is (n_queries, tile_n).

Like the reference kernel, returned distances are *squared* L2; the sqrt
fixup for L2Sqrt metrics is the caller's postprocess step
(knn_brute_force_faiss.cuh:367-380).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.utils import ceildiv


def fused_l2_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    tile_n: int = 8192,
    precision: str = "highest",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows per query under squared L2.

    Parameters
    ----------
    index:
        (n_index, d) database rows.
    queries:
        (n_queries, d) query rows.
    k:
        Neighbors per query (k <= n_index).
    tile_n:
        Index rows per scan step; bounds the live distance tile to
        (n_queries, tile_n).

    Returns
    -------
    (distances, indices): (n_queries, k) squared-L2 distances sorted
    ascending and int32 index-row ids.
    """
    expects(index.ndim == 2 and queries.ndim == 2 and index.shape[1] == queries.shape[1],
            "fused_l2_knn: shape mismatch")
    n = index.shape[0]
    expects(0 < k <= n, "fused_l2_knn: k=%d out of range for n_index=%d", k, n)
    nq = queries.shape[0]

    tile_n = max(k, min(tile_n, n))
    n_tiles = ceildiv(n, tile_n)
    n_pad = n_tiles * tile_n

    qn = jnp.sum(queries * queries, axis=1)
    xn = jnp.sum(index * index, axis=1)
    # padded rows get +inf norms so they can never be selected
    x_p = jnp.pad(index, ((0, n_pad - n), (0, 0)))
    xn_p = jnp.pad(xn, (0, n_pad - n), constant_values=jnp.inf)

    def step(carry, tile_idx):
        best_d, best_i = carry
        j0 = tile_idx * tile_n
        x_t = lax.dynamic_slice_in_dim(x_p, j0, tile_n, axis=0)
        xn_t = lax.dynamic_slice_in_dim(xn_p, j0, tile_n, axis=0)
        d = qn[:, None] + xn_t[None, :] - 2.0 * jnp.matmul(
            queries, x_t.T, precision=precision)
        d = jnp.maximum(d, 0.0)
        d = jnp.where(jnp.isfinite(xn_t)[None, :], d, jnp.inf)
        kk = min(k, tile_n)
        t_vals, t_idx = lax.top_k(-d, kk)
        t_idx = (j0 + t_idx).astype(jnp.int32)
        # merge running and tile top-k: 2k-wide re-selection
        cat_d = jnp.concatenate([best_d, -t_vals], axis=1)
        cat_i = jnp.concatenate([best_i, t_idx], axis=1)
        m_vals, m_pos = lax.top_k(-cat_d, k)
        m_idx = jnp.take_along_axis(cat_i, m_pos, axis=1)
        return (-m_vals, m_idx), None

    init = (jnp.full((nq, k), jnp.inf, dtype=jnp.result_type(queries.dtype, jnp.float32)),
            jnp.full((nq, k), jnp.iinfo(jnp.int32).max, dtype=jnp.int32))
    (best_d, best_i), _ = lax.scan(step, init, jnp.arange(n_tiles))
    return best_d, best_i
