"""Fused L2 distance + k-nearest-neighbor selection.

Reference: ``fusedL2Knn`` (cpp/include/raft/spatial/knn/detail/
fused_l2_knn.cuh:196,946) — one CUDA kernel computes an L2 distance tile
and immediately runs a warp-select top-k over it, dumping intermediate
top-ks to shared memory and merging across tiles (the usePrevTopKs path),
so the (n_queries, n_index) distance matrix never exists in memory.
It is the fast path of ``brute_force_knn`` for k ≤ 64 / L2 / row-major
(detail/knn_brute_force_faiss.cuh:297-313).

TPU re-design, two implementations sharing the same contract:

- ``impl="xla"``: the shared tile-scan driver
  (:mod:`raft_tpu.spatial.tiled_knn`) with an MXU-matmul distance tile
  in the expanded ``qn + xn − 2·q@xᵀ`` form.  The reference's
  smem-merge becomes a (k + k)-wide re-selection per tile; high-water
  memory is (n_queries, tile_n), which round-trips HBM per tile.
- ``impl="pallas"``: the fully fused kernel
  (:mod:`raft_tpu.ops.knn_tile`) — distance tile and running top-k both
  VMEM-resident, threshold-gated bitonic merge, the true analog of the
  reference's one-kernel design.
- ``impl="xla_fused"``: the XLA-composed fused twin
  (:func:`raft_tpu.ops.knn_tile.fused_knn_xla`) — the kernel's tile
  geometry and distance arithmetic with an exact per-tile
  ``lax.top_k`` running merge: one program, no (nq, n) matrix, the
  off-TPU production fallback.  (The op-for-op bitwise oracle is
  ``fused_knn_xla_oracle``, tests only.)
- ``impl=None`` (default): "xla" everywhere as of r4 — the one honest
  steady-state measurement (100k×1024q k=100, v5e) put the tile-scan
  at 1.74 s vs the fused kernel's 4.01 s, so the default follows the
  evidence until `tools/knn_kernel_sweep.py` finds a winning kernel
  geometry (docs/TUNING.md "Open question").  Opt into the kernel with
  ``impl="pallas"`` / ``RAFT_TPU_FUSED_KNN_IMPL=pallas``.

Like the reference kernel, returned distances are *squared* L2; the sqrt
fixup for L2Sqrt metrics is the caller's postprocess step
(knn_brute_force_faiss.cuh:367-380).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.spatial.tiled_knn import tiled_knn


def fused_l2_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    tile_n: int = 8192,
    precision: str = "highest",
    impl: Optional[str] = None,
    donate_queries: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows per query under squared L2.

    Parameters
    ----------
    index:
        (n_index, d) database rows.
    queries:
        (n_queries, d) query rows.
    k:
        Neighbors per query (k <= n_index).
    tile_n:
        Index rows per scan step; bounds the live distance tile to
        (n_queries, tile_n) (xla impl) / the kernel index-block (pallas).
    impl:
        "xla", "pallas", "xla_fused" (the XLA-composed fused twin of
        the kernel — one program, off-TPU production fallback,
        ops/knn_tile.py), or None = pick per backend (see module doc).
        Env override: RAFT_TPU_FUSED_KNN_IMPL.
    donate_queries:
        Consume the queries buffer (the xla scan path donates it to
        its executable and recycles the storage — the caller must own
        the buffer and not reuse it; docs/ZERO_COPY.md).  Ignored on
        the pallas path, which has no donating kernel build.

    Returns
    -------
    (distances, indices): (n_queries, k) squared-L2 distances sorted
    ascending and int32 index-row ids.
    """
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "fused_l2_knn: shape mismatch")
    # registry resolution (override → configure → env → tuning table →
    # default); unset default = per-backend auto, currently "xla"
    # everywhere — the r4 measured default (module doc).  The k <= 128
    # Pallas cap (the kernel's bitonic merge is a network over 2*kpad
    # lanes; beyond kpad=128 the unrolled network blows up Mosaic
    # compile time — the reference draws the line even tighter,
    # fusedL2Knn serving only k <= 64, knn_brute_force_faiss.cuh:
    # 297-313) is the registry's legality predicate: an explicit pallas
    # request above it errors rather than silently running another impl.
    impl = tuning.resolve("fused_knn_impl", impl, site="fused_l2_knn",
                          n=index.shape[0], k=k,
                          dtype=index.dtype) or "xla"
    if impl == "pallas":
        from raft_tpu.ops.knn_tile import fused_knn_tile

        # tile shape comes from the knn_block_q/knn_block_n registry
        # knobs inside the kernel entry — no consumer-local literal, so
        # swept winners reach this call site (ci/style_check.py bans
        # re-introducing one)
        return fused_knn_tile(index, queries, k, precision=precision)
    if impl == "xla_fused":
        from raft_tpu.ops.knn_tile import fused_knn_xla

        return fused_knn_xla(index, queries, k, precision=precision)
    # stable tile-dist identity: a per-call closure would retrace the
    # whole tiled scan every call (r5 retrace audit); the precision
    # variant is lru-memoized and the query norms ride along as a
    # Partial operand, so repeat calls at a shape are pure cache hits
    # qn reads queries BEFORE the (possibly donating) scan call; the
    # runtime keeps the buffer alive for this already-dispatched read
    qn = jnp.sum(queries * queries, axis=1)
    tile_dist = jax.tree_util.Partial(_l2_tile_dist(precision), qn)
    return tiled_knn(index, queries, k, tile_dist, tile_n=tile_n,
                     donate_queries=donate_queries)


@functools.lru_cache(maxsize=None)
def _l2_tile_dist(precision: str):
    def f(qn, q, x_t):
        xn_t = jnp.sum(x_t * x_t, axis=1)
        d = qn[:, None] + xn_t[None, :] - 2.0 * jnp.matmul(
            q, x_t.T, precision=precision)
        return jnp.maximum(d, 0.0)
    return f
