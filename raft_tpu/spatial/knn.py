"""Brute-force k-nearest-neighbors over partitioned inputs.

Reference: ``brute_force_knn`` (cpp/include/raft/spatial/knn/knn.hpp:127)
→ ``brute_force_knn_impl`` (detail/knn_brute_force_faiss.cuh:220): build
id-range translations, preprocess data per metric, search each index
partition on a pooled stream — fusedL2Knn fast path for L2 (:297-313),
haversine kernel (:319), FAISS bfKnn otherwise (:325-350) — then
heap-merge partition results (``knn_merge_parts``, :55,162) and
postprocess distances (sqrt / 1/p-root fixup, :367-380).

TPU re-design:

- Partition searches are independent jitted computations; XLA's async
  dispatch overlaps them the way the reference's stream pool does
  (``handle.get_next_usable_stream``).
- The FAISS fallback becomes ``pairwise_distance`` (Pallas/MXU) +
  ``select_k`` — no third-party dependency.
- ``knn_merge_parts``'s per-row heap over n_parts·k candidates becomes a
  single (n_parts·k)-wide re-selection, with id translation applied
  vectorised instead of per-thread.
- Selection direction is metric-aware: inner-product-family metrics
  select max (FAISS METRIC_INNER_PRODUCT, common_faiss.h:30-55); cosine /
  correlation are converted to ``1 - sim`` distances (processing.hpp:109)
  *before* the merge so every merge is a min-merge.

Indices are int32 — 2^31 rows per partition is beyond single-chip HBM,
and int32 keeps selection payloads on the fast vector path (the reference
uses int64_t for Dask-global ids; the MNMG layer widens at the boundary).
"""

from __future__ import annotations

import functools
import numbers
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects, fail
from raft_tpu.core.handle import record_on_handle
from raft_tpu.core.profiler import profiled
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.spatial.fused_l2_knn import fused_l2_knn
from raft_tpu.spatial.haversine import haversine_knn
from raft_tpu.spatial.processing import create_processor
from raft_tpu.spatial.select_k import select_k

D = DistanceType

_L2_FAMILY = (D.L2Expanded, D.L2SqrtExpanded, D.L2Unexpanded, D.L2SqrtUnexpanded)
_IP_FAMILY = (D.InnerProduct,)
_SIM_FAMILY = (D.CosineExpanded, D.CorrelationExpanded)


def knn_merge_parts(
    part_distances: jnp.ndarray,
    part_indices: jnp.ndarray,
    k: int,
    translations: Optional[Sequence[int]] = None,
    select_min: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-partition kNN results into a global top-k.

    Reference knn_merge_parts_kernel (detail/knn_brute_force_faiss.cuh:55):
    a per-row block-select heap over ``n_parts * k`` candidates with
    partition id translations added on insert.

    Parameters
    ----------
    part_distances, part_indices:
        (n_parts, n_queries, k) stacked per-partition results.
    translations:
        Per-partition id offsets added to ``part_indices`` (reference
        ``translations`` device array).  None → no translation.

    Returns
    -------
    (distances, indices): (n_queries, k) globally merged, best-first.
    """
    expects(part_distances.ndim == 3 and part_indices.shape == part_distances.shape,
            "knn_merge_parts: (n_parts, n_queries, k) inputs required")
    n_parts, nq, kk = part_distances.shape
    expects(k <= n_parts * kk, "knn_merge_parts: k=%d > total candidates", k)
    idx = part_indices
    if translations is not None:
        expects(len(translations) == n_parts,
                "knn_merge_parts: %d translations for %d partitions",
                len(translations), n_parts)
        trans = jnp.asarray(translations, dtype=part_indices.dtype)
        idx = idx + trans[:, None, None]
    # (n_parts, nq, k) -> (nq, n_parts*k) candidate lists
    cand_d = jnp.transpose(part_distances, (1, 0, 2)).reshape(nq, n_parts * kk)
    cand_i = jnp.transpose(idx, (1, 0, 2)).reshape(nq, n_parts * kk)
    return select_k(cand_d, k, select_min=select_min, values=cand_i)


@functools.partial(jax.jit, static_argnames=("k",))
def _exact_rerank_l2(part, queries, cand_ids, k):
    """Exact f32 re-rank of stage-1 candidates (squared L2).

    The speed half of the bf16+rerank mode (reference analog: FAISS
    IndexRefineFlat via ann_quantized_faiss.cuh:75, and fused_l2_knn.cuh
    :196's own precision trade): gather the (nq, k2) candidate rows and
    recompute their distances elementwise in f32 — ~2·nq·k2·d FLOPs,
    trivial next to the scan; the gather moves k2/n of the index.
    """
    vecs = part[jnp.clip(cand_ids, 0, part.shape[0] - 1)]   # (nq, k2, d)
    diff = vecs.astype(jnp.float32) - queries.astype(jnp.float32)[:, None]
    dist = jnp.sum(diff * diff, axis=-1)
    return select_k(dist, k, select_min=True, values=cand_ids)


def _search_one_partition(
    part: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    metric: DistanceType,
    metric_arg: float,
    tile_n: int,
    precision: str = "highest",
    rerank_ratio: int = 1,
    donate_queries: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search a single index partition; returns (distances, int32 indices).

    Distances are in pre-postprocess form for the L2 family (squared),
    final form for everything else.
    """
    if metric in _L2_FAMILY:
        if rerank_ratio > 1:
            # two-stage: single-pass-bf16 scan over k*ratio candidates,
            # exact f32 re-rank to k.  Exact whenever the true top-k
            # survive stage 1 (the bench's rerank rung reports measured
            # recall next to the speed)
            # impl pinned to "xla": k2 routinely exceeds the pallas
            # kernel's k <= 128 merge-width cap, so a config-level
            # pallas pin (which the user set for their OWN k) must not
            # leak into the internal widened stage-1 scan
            k2 = min(k * rerank_ratio, part.shape[0])
            _, i1 = fused_l2_knn(part, queries, k2, tile_n=tile_n,
                                 precision="default", impl="xla")
            return _exact_rerank_l2(part, queries, i1, k)
        # fast path, reference :297-313; squared distances
        return fused_l2_knn(part, queries, k, tile_n=tile_n,
                            precision=precision,
                            donate_queries=donate_queries)
    if metric == D.Haversine:
        expects(queries.shape[1] == 2,
                "Haversine distance requires 2 dimensions (latitude / longitude).")
        return haversine_knn(part, queries, k, tile_n=tile_n)
    if metric in _SIM_FAMILY:
        proc = create_processor(metric)
        q = proc.preprocess(queries)
        p = proc.preprocess(part)
        sim = jnp.matmul(q, p.T, precision=precision)
        # 1 - sim before selection: monotone-reversing, so min-select on
        # distances == the reference's max-select on similarities
        return select_k(proc.postprocess(sim), k, select_min=True)
    if metric in _IP_FAMILY:
        ip = jnp.matmul(queries, part.T, precision=precision)
        return select_k(ip, k, select_min=False)
    # generic metric: full pairwise tile + selection (FAISS bfKnn
    # analog).  pairwise_distance's matmul-backed metrics read the
    # module-global precision, so pin it to this call's request for the
    # duration — otherwise precision= would be a silent no-op here
    from raft_tpu.distance.pairwise import (_DEFAULT_PRECISION,
                                            set_default_precision)

    prev = _DEFAULT_PRECISION
    set_default_precision(precision)
    try:
        dist = pairwise_distance(queries, part, metric,
                                 metric_arg=metric_arg)
    finally:
        set_default_precision(prev)
    return select_k(dist, k, select_min=True)


@profiled("spatial")
def brute_force_knn(
    inputs: Union[jnp.ndarray, List[jnp.ndarray]],
    queries: jnp.ndarray,
    k: int,
    metric: DistanceType = D.L2Expanded,
    metric_arg: float = 2.0,
    translations: Optional[Sequence[int]] = None,
    tile_n: int = 8192,
    precision: str = "highest",
    rerank_ratio: int = 1,
    donate_queries: bool = False,
    handle=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN of ``queries`` against one or more index partitions.

    Reference brute_force_knn (knn.hpp:127 / detail impl :220).

    Parameters
    ----------
    inputs:
        A single (n, d) index array or a list of (n_i, d) partitions.
    queries:
        (n_queries, d) search items.
    k:
        Neighbors per query.
    metric, metric_arg:
        Distance metric (metric_arg is the Minkowski p).
    translations:
        Optional per-partition global-id offsets; defaults to cumulative
        partition starts (reference id_ranges, :241-255).
    tile_n:
        Index tile size for the scanned L2/haversine paths.
    precision:
        MXU matmul precision for the distance dot products: "highest"
        (default, f32-accurate via multi-pass bf16) or "default"
        (single-pass bf16 — the TF32-tensor-core-class speed/accuracy
        trade; the reference's cublas math-mode analog).
    rerank_ratio:
        L2-family only.  > 1 enables the two-stage mode: a single-pass
        bf16 scan keeps ``k * rerank_ratio`` candidates per partition,
        then an exact f32 re-rank reduces them to k (the bf16 speed at
        ~recall-1.0 accuracy; candidates the bf16 rounding dropped from
        stage 1 are the only possible misses).  NOTE: with
        ``rerank_ratio > 1`` stage 1 always runs single-pass bf16
        (``precision="default"``) REGARDLESS of this call's
        ``precision`` argument — bf16 speed is the mode's entire point,
        and ``precision`` governs only the single-stage path; the f32
        re-rank restores exactness for every candidate that survived
        stage 1.
    donate_queries:
        Consume the queries buffer — the single-partition L2 scan path
        donates it to its executable and recycles the storage; the
        caller must own the buffer and not reuse it after the call
        (the serve layer's padded batch is the intended consumer,
        docs/ZERO_COPY.md).  A no-op on paths without a donating
        executable (multi-partition, rerank, non-L2 metrics).
    handle:
        Optional :class:`raft_tpu.core.handle.Handle`.  Each partition's
        search is recorded on the next pool stream (the reference forks
        partitions across the stream pool, knn_brute_force_faiss.cuh:
        289-297) — XLA's async dispatch overlaps the independent searches,
        and ``handle.sync_stream_pool()`` blocks on exactly that work;
        the merged result lands on the handle's main stream.

    Returns
    -------
    (distances, indices): (n_queries, k); indices are global (translated)
    int32 ids; distances in final (post-processed) form.
    """
    parts = [inputs] if not isinstance(inputs, (list, tuple)) else list(inputs)
    expects(len(parts) > 0, "brute_force_knn: no input partitions")
    for p in parts:
        expects(p.ndim == 2 and p.shape[1] == queries.shape[1],
                "brute_force_knn: partition/query dimensionality mismatch")

    if translations is None:
        translations = []
        total = 0
        for p in parts:
            translations.append(total)
            total += p.shape[0]

    expects(isinstance(rerank_ratio, numbers.Integral)
            and not isinstance(rerank_ratio, bool) and rerank_ratio >= 1,
            "brute_force_knn: rerank_ratio must be an integer >= 1, got %r",
            rerank_ratio)
    rerank_ratio = int(rerank_ratio)
    expects(rerank_ratio == 1 or metric in _L2_FAMILY,
            "brute_force_knn: rerank_ratio applies to the L2 family only")
    select_min = metric not in _IP_FAMILY
    # donation is legal only when exactly ONE consumer reads the
    # queries buffer: a multi-partition search (or the rerank mode's
    # two-stage read) would replay a consumed buffer
    donate_queries = (donate_queries and len(parts) == 1
                      and rerank_ratio == 1)
    results = []
    for i, p in enumerate(parts):
        r = _search_one_partition(p, queries, k, metric, metric_arg, tile_n,
                                  precision, rerank_ratio=rerank_ratio,
                                  donate_queries=donate_queries)
        if handle is not None:
            handle.get_next_usable_stream(i).record(*r)
        results.append(r)
    if len(parts) == 1:
        dist, idx = results[0]
        t0 = int(translations[0])
        if t0 != 0:
            idx = idx + t0
    else:
        part_d = jnp.stack([d for d, _ in results])
        part_i = jnp.stack([i for _, i in results])
        dist, idx = knn_merge_parts(part_d, part_i, k, translations,
                                    select_min=select_min)

    # sqrt / Lp-root fixup after the merge (reference :367-380); merge
    # order is unaffected because the maps are monotone
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(jnp.maximum(dist, 0.0))
    record_on_handle(handle, dist, idx)
    return dist, idx
