"""Spatial / k-nearest-neighbor primitives.

TPU-native re-design of the reference ``raft/spatial/knn`` module
(cpp/include/raft/spatial/knn/): brute-force kNN with partitioned inputs
and heap-merge, fused L2 kNN, k-selection, haversine kNN, metric
processors, random-ball-cover ANN and IVF quantized ANN.
"""

from raft_tpu.spatial.select_k import select_k  # noqa: F401
from raft_tpu.spatial.fused_l2_knn import fused_l2_knn  # noqa: F401
from raft_tpu.spatial.haversine import haversine_distances, haversine_knn  # noqa: F401
from raft_tpu.spatial.knn import brute_force_knn, knn_merge_parts  # noqa: F401
from raft_tpu.spatial.processing import create_processor  # noqa: F401
