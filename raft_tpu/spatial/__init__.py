"""Spatial / k-nearest-neighbor primitives.

TPU-native re-design of the reference ``raft/spatial/knn`` module
(cpp/include/raft/spatial/knn/): brute-force kNN with partitioned inputs
and heap-merge, fused L2 kNN, k-selection, haversine kNN, metric
processors, random-ball-cover ANN and IVF quantized ANN.
"""

from raft_tpu.spatial.select_k import select_k  # noqa: F401
from raft_tpu.spatial.fused_l2_knn import fused_l2_knn  # noqa: F401
from raft_tpu.spatial.haversine import haversine_distances, haversine_knn  # noqa: F401
from raft_tpu.spatial.knn import brute_force_knn, knn_merge_parts  # noqa: F401
from raft_tpu.spatial.processing import create_processor  # noqa: F401
from raft_tpu.spatial.ann import (  # noqa: F401
    IVFFlatParams, IVFPQParams, IVFSQParams,
    approx_knn_build_index, approx_knn_search,
    ivf_flat_build, ivf_flat_search,
    ivf_pq_build, ivf_pq_search,
    ivf_sq_build, ivf_sq_search,
)
from raft_tpu.spatial.ball_cover import (  # noqa: F401
    BallCoverIndex, rbc_build_index, rbc_knn_query, rbc_all_knn_query,
)
from raft_tpu.spatial.mnmg_knn import mnmg_knn  # noqa: F401
from raft_tpu.spatial.ooc import (  # noqa: F401
    OocIVFFlat, ivf_flat_to_ooc, ooc_extend, ooc_ivf_flat_search,
)
