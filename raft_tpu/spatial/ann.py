"""Approximate nearest neighbors: IVF-Flat, IVF-PQ, IVF-SQ — native.

Reference: spatial/knn/ann.hpp:45,71 (``approx_knn_build_index`` /
``approx_knn_search``) with params ``IVFParam``/``IVFPQParam``/``IVFSQParam``
(ann_common.h:42-72).  The reference delegates build+search entirely to
FAISS GPU (detail/ann_quantized_faiss.cuh:75+); the TPU build implements
the quantizers natively (SURVEY.md §7.8):

- **IVF-Flat**: k-means coarse quantizer (reusing spectral/kmeans) +
  padded per-list storage.  Lists are a dense (nlist, max_len, d) tensor —
  scanning ``nprobe`` lists per query is a batched matmul on the MXU, the
  TPU-shaped substitute for FAISS's warp-level list scans.
- **IVF-PQ**: product quantization of residuals (M subspaces × 2^n_bits
  codes, k-means codebooks); search = per-query ADC lookup tables, code
  gathers, segment sums.
- **IVF-SQ**: per-dimension 8-bit scalar quantization of residuals (the
  QT_8bit family) scanned like IVF-Flat after dequantization.

All searches return (distances, ids) best-first like brute_force_knn.
L2 metrics are supported (reference FAISS path likewise restricts the
metric set, ann_quantized_faiss.cuh:94-118).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import expanded_sq_dists
from raft_tpu.spatial.select_k import select_k
from raft_tpu.spectral.kmeans import kmeans

D = DistanceType


# --------------------------------------------------------------------- #
# params (reference ann_common.h:42-72)
# --------------------------------------------------------------------- #
@dataclass
class IVFFlatParams:
    nlist: int
    nprobe: int = 8


@dataclass
class IVFPQParams:
    nlist: int
    nprobe: int = 8
    M: int = 8           # subquantizers
    n_bits: int = 8      # log2 codebook size


@dataclass
class IVFSQParams:
    nlist: int
    nprobe: int = 8
    qtype: str = "QT_8bit"
    encode_residual: bool = True


class IVFFlatIndex(NamedTuple):
    centroids: jnp.ndarray   # (nlist, d)
    lists: jnp.ndarray       # (nlist, max_len, d) padded vectors
    list_ids: jnp.ndarray    # (nlist, max_len) original row ids, -1 pad
    list_sizes: jnp.ndarray  # (nlist,)
    metric: DistanceType
    nprobe: int              # default probe count from build params


class IVFPQIndex(NamedTuple):
    centroids: jnp.ndarray    # (nlist, d) coarse
    codebooks: jnp.ndarray    # (M, ksub, dsub) per-subspace codewords
    codes: jnp.ndarray        # (nlist, max_len, M) uint8/int32 codes
    list_ids: jnp.ndarray     # (nlist, max_len)
    list_sizes: jnp.ndarray
    metric: DistanceType
    nprobe: int


class IVFSQIndex(NamedTuple):
    centroids: jnp.ndarray
    q_data: jnp.ndarray       # (nlist, max_len, d) quantized residuals
    scale: jnp.ndarray        # (d,) dequant scale
    offset: jnp.ndarray       # (d,) dequant offset
    list_ids: jnp.ndarray
    list_sizes: jnp.ndarray
    metric: DistanceType
    nprobe: int
    encode_residual: bool     # build-time setting, honored by search


# --------------------------------------------------------------------- #
# shared coarse quantizer plumbing
# --------------------------------------------------------------------- #
def _coarse_assign(X, nlist, seed):
    """k-means coarse quantizer + list assignment."""
    res = kmeans(X, nlist, seed=seed, max_iter=25)
    return res.centroids, res.labels


def _build_lists(labels: np.ndarray, nlist: int) -> Tuple[np.ndarray, int]:
    """Host: (nlist, max_len) row-id table, -1 padded; max_len is sized to
    the largest list so nothing is ever truncated.

    Native path: cpp/src/host_runtime.cpp rt_build_lists (the sequential
    packing loop); Python fallback below.
    """
    labels = np.asarray(labels)
    from raft_tpu.core import native
    nat = native.build_lists(labels, nlist)
    if nat is not None:
        table64, ml = nat
        return table64.astype(np.int32), ml
    counts = np.bincount(labels, minlength=nlist)
    ml = max(int(counts.max()), 1)
    table = np.full((nlist, ml), -1, np.int32)
    fill = np.zeros(nlist, np.int64)
    for i, l in enumerate(labels):
        if fill[l] < ml:
            table[l, fill[l]] = i
            fill[l] += 1
    return table, ml


_L2_METRICS = (D.L2Expanded, D.L2SqrtExpanded, D.L2Unexpanded,
               D.L2SqrtUnexpanded)


def _check_metric(name, metric):
    expects(metric in _L2_METRICS,
            "%s: unsupported metric %d — the IVF quantizers are L2-only "
            "(the reference FAISS path likewise restricts the metric set, "
            "ann_quantized_faiss.cuh:94-118)", name, int(metric))


def _search_lists(q, centroids, list_vecs, list_ids, k, nprobe, metric):
    """Shared IVF search driver: probe → gather → distance → select.

    q: (nq, d).  list_vecs: (nlist, max_len, d).  Returns (dists, ids).
    """
    nlist, max_len, d = list_vecs.shape
    nprobe = min(nprobe, nlist)
    # (nq, nlist) query-centroid distances → top-nprobe lists
    qc = expanded_sq_dists(q, centroids)
    _, probes = select_k(qc, nprobe, select_min=True)         # (nq, nprobe)

    cand_vecs = list_vecs[probes]          # (nq, nprobe, max_len, d)
    cand_ids = list_ids[probes]            # (nq, nprobe, max_len)
    nq = q.shape[0]
    cand_vecs = cand_vecs.reshape(nq, nprobe * max_len, d)
    cand_ids = cand_ids.reshape(nq, nprobe * max_len)

    dist = (jnp.sum(q * q, 1)[:, None]
            + jnp.sum(cand_vecs * cand_vecs, -1)
            - 2.0 * jnp.einsum("nd,nmd->nm", q, cand_vecs,
                               precision="highest"))
    dist = jnp.maximum(dist, 0.0)
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(dist)
    dist = jnp.where(cand_ids >= 0, dist, jnp.inf)
    dd, ii = select_k(dist, k, select_min=True, values=cand_ids)
    return dd, ii


# --------------------------------------------------------------------- #
# IVF-Flat
# --------------------------------------------------------------------- #
def ivf_flat_build(X, params: IVFFlatParams,
                   metric: DistanceType = D.L2Expanded,
                   seed: int = 1234) -> IVFFlatIndex:
    """Build an IVF-Flat index (reference approx_knn_build_index IVFFlat
    path, ann_quantized_faiss.cuh:129-141)."""
    X = jnp.asarray(X)
    m, d = X.shape
    expects(params.nlist <= m, "ivf_flat_build: nlist > n_vectors")
    _check_metric("ivf_flat_build", metric)
    centroids, labels = _coarse_assign(X, params.nlist, seed)
    table, max_len = _build_lists(np.asarray(labels), params.nlist)
    table_j = jnp.asarray(table)
    gather = jnp.where(table_j >= 0, table_j, 0)
    lists = X[gather] * (table_j >= 0)[..., None]
    return IVFFlatIndex(centroids, lists, table_j,
                        jnp.asarray((table >= 0).sum(1), jnp.int32), metric,
                        params.nprobe)


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "metric"))
def _ivf_flat_search_jit(centroids, lists, list_ids, q, k, nprobe, metric):
    return _search_lists(q, centroids, lists, list_ids, k, nprobe, metric)


def ivf_flat_search(index: IVFFlatIndex, queries, k: int,
                    nprobe: Optional[int] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search an IVF-Flat index (reference approx_knn_search, ann.hpp:71);
    ``nprobe`` defaults to the build params' value."""
    q = jnp.asarray(queries)
    nprobe = index.nprobe if nprobe is None else nprobe
    expects(nprobe >= 1, "ivf_flat_search: nprobe must be >= 1")
    return _ivf_flat_search_jit(index.centroids, index.lists, index.list_ids,
                                q, k, nprobe,
                                DistanceType(int(index.metric)))


# --------------------------------------------------------------------- #
# IVF-PQ
# --------------------------------------------------------------------- #
def ivf_pq_build(X, params: IVFPQParams,
                 metric: DistanceType = D.L2Expanded,
                 seed: int = 1234) -> IVFPQIndex:
    """Build IVF-PQ: coarse quantize, then per-subspace k-means codebooks
    over residuals (reference IVFPQ path, ann_quantized_faiss.cuh:143-160)."""
    X = jnp.asarray(X)
    m, d = X.shape
    M, ksub = params.M, 2 ** params.n_bits
    expects(d % M == 0, "ivf_pq_build: dim %d not divisible by M=%d", d, M)
    _check_metric("ivf_pq_build", metric)
    dsub = d // M
    centroids, labels = _coarse_assign(X, params.nlist, seed)
    resid = X - centroids[labels]

    codebooks = []
    codes_flat = []
    for mi in range(M):
        sub = resid[:, mi * dsub:(mi + 1) * dsub]
        kk = min(ksub, m)
        res = kmeans(sub, kk, seed=seed + mi, max_iter=20)
        cb = res.centroids
        if kk < ksub:  # pad codebook
            cb = jnp.concatenate(
                [cb, jnp.full((ksub - kk, dsub), jnp.inf, cb.dtype)])
        codebooks.append(cb)
        codes_flat.append(res.labels)
    codebooks = jnp.stack(codebooks)                  # (M, ksub, dsub)
    codes_flat = jnp.stack(codes_flat, axis=1)        # (m, M)

    table, max_len = _build_lists(np.asarray(labels), params.nlist)
    table_j = jnp.asarray(table)
    gather = jnp.where(table_j >= 0, table_j, 0)
    codes = codes_flat[gather]                        # (nlist, max_len, M)
    return IVFPQIndex(centroids, codebooks, codes, table_j,
                      jnp.asarray((table >= 0).sum(1), jnp.int32), metric,
                      params.nprobe)


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "metric"))
def _ivf_pq_search_jit(centroids, codebooks, all_codes, list_ids, q, k,
                       nprobe, metric):
    nlist, max_len, M = all_codes.shape
    _, ksub, dsub = codebooks.shape
    nq, d = q.shape
    nprobe = min(nprobe, nlist)

    qc = expanded_sq_dists(q, centroids)
    _, probes = select_k(qc, nprobe, select_min=True)   # (nq, nprobe)

    # ADC tables per (query, probed list): residual = q - centroid, so the
    # lookup table depends on the probe; table[nq, nprobe, M, ksub] =
    # ||resid_sub - codeword||^2
    resid = q[:, None, :] - centroids[probes]           # (nq, nprobe, d)
    rs = resid.reshape(nq, nprobe, M, dsub)
    cb = codebooks                                      # (M, ksub, dsub)
    lut = (jnp.sum(rs * rs, -1)[..., None]
           + jnp.sum(cb * cb, -1)[None, None]
           - 2.0 * jnp.einsum("npmd,mkd->npmk", rs, cb,
                              precision="highest"))     # (nq,nprobe,M,ksub)

    codes = all_codes[probes]                           # (nq,nprobe,max_len,M)
    ids = list_ids[probes].reshape(nq, nprobe * max_len)
    # gather LUT entries: dist = sum_m lut[m, code_m]; align code axis with
    # the LUT's ksub axis to gather without materializing a ksub-sized copy
    codes_t = jnp.transpose(codes, (0, 1, 3, 2)).astype(jnp.int32)
    dist = jnp.take_along_axis(lut, codes_t, axis=-1)   # (nq,np,M,max_len)
    dist = jnp.sum(dist, axis=2).reshape(nq, nprobe * max_len)
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(jnp.maximum(dist, 0.0))
    dist = jnp.where(ids >= 0, dist, jnp.inf)
    return select_k(dist, k, select_min=True, values=ids)


def ivf_pq_search(index: IVFPQIndex, queries, k: int,
                  nprobe: Optional[int] = None):
    q = jnp.asarray(queries)
    nprobe = index.nprobe if nprobe is None else nprobe
    expects(nprobe >= 1, "ivf_pq_search: nprobe must be >= 1")
    return _ivf_pq_search_jit(index.centroids, index.codebooks, index.codes,
                              index.list_ids, q, k, nprobe,
                              DistanceType(int(index.metric)))


# --------------------------------------------------------------------- #
# IVF-SQ
# --------------------------------------------------------------------- #
def ivf_sq_build(X, params: IVFSQParams,
                 metric: DistanceType = D.L2Expanded,
                 seed: int = 1234) -> IVFSQIndex:
    """8-bit scalar quantization of residuals (QT_8bit; reference IVFSQ
    path, ann_quantized_faiss.cuh:162-176)."""
    expects(params.qtype in ("QT_8bit", "QT_8bit_uniform"),
            "ivf_sq_build: unsupported qtype %s", params.qtype)
    _check_metric("ivf_sq_build", metric)
    X = jnp.asarray(X)
    centroids, labels = _coarse_assign(X, params.nlist, seed)
    resid = X - centroids[labels] if params.encode_residual else X
    lo = jnp.min(resid, axis=0)
    hi = jnp.max(resid, axis=0)
    if params.qtype == "QT_8bit_uniform":
        lo = jnp.full_like(lo, jnp.min(lo))
        hi = jnp.full_like(hi, jnp.max(hi))
    scale = (hi - lo) / 255.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q_all = jnp.clip(jnp.round((resid - lo) / scale), 0, 255).astype(jnp.uint8)

    table, _ = _build_lists(np.asarray(labels), params.nlist)
    table_j = jnp.asarray(table)
    gather = jnp.where(table_j >= 0, table_j, 0)
    q_data = q_all[gather]
    return IVFSQIndex(centroids, q_data, scale, lo, table_j,
                      jnp.asarray((table >= 0).sum(1), jnp.int32), metric,
                      params.nprobe, params.encode_residual)


@functools.partial(jax.jit, static_argnames=("k", "nprobe",
                                             "encode_residual", "metric"))
def _ivf_sq_search_jit(centroids, q_data, scale, offset, list_ids,
                       q, k, nprobe, encode_residual, metric):
    nlist, max_len, d = q_data.shape
    nq = q.shape[0]
    nprobe = min(nprobe, nlist)
    # probe, then dequantize only the probed lists (the whole store stays
    # uint8 in HBM — the memory point of scalar quantization)
    qc = expanded_sq_dists(q, centroids)
    _, probes = select_k(qc, nprobe, select_min=True)       # (nq, nprobe)
    deq = (q_data[probes].astype(jnp.float32) * scale + offset)
    if encode_residual:
        deq = deq + centroids[probes][:, :, None, :]
    cand = deq.reshape(nq, nprobe * max_len, d)
    ids = list_ids[probes].reshape(nq, nprobe * max_len)
    dist = (jnp.sum(q * q, 1)[:, None] + jnp.sum(cand * cand, -1)
            - 2.0 * jnp.einsum("nd,nmd->nm", q, cand, precision="highest"))
    dist = jnp.maximum(dist, 0.0)
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(dist)
    dist = jnp.where(ids >= 0, dist, jnp.inf)
    return select_k(dist, k, select_min=True, values=ids)


def ivf_sq_search(index: IVFSQIndex, queries, k: int,
                  nprobe: Optional[int] = None):
    """Search; honors the build-time ``encode_residual`` setting."""
    q = jnp.asarray(queries)
    nprobe = index.nprobe if nprobe is None else nprobe
    expects(nprobe >= 1, "ivf_sq_search: nprobe must be >= 1")
    return _ivf_sq_search_jit(index.centroids, index.q_data, index.scale,
                              index.offset, index.list_ids,
                              q, k, nprobe,
                              bool(index.encode_residual),
                              DistanceType(int(index.metric)))


# --------------------------------------------------------------------- #
# dispatcher (reference ann.hpp:45,71)
# --------------------------------------------------------------------- #
def approx_knn_build_index(X, params, metric: DistanceType = D.L2Expanded,
                           seed: int = 1234):
    if isinstance(params, IVFPQParams):
        return ivf_pq_build(X, params, metric, seed)
    if isinstance(params, IVFSQParams):
        return ivf_sq_build(X, params, metric, seed)
    if isinstance(params, IVFFlatParams):
        return ivf_flat_build(X, params, metric, seed)
    raise TypeError(f"unknown ANN params {type(params)}")


def approx_knn_search(index, queries, k: int, nprobe: Optional[int] = None):
    if isinstance(index, IVFPQIndex):
        return ivf_pq_search(index, queries, k, nprobe)
    if isinstance(index, IVFSQIndex):
        return ivf_sq_search(index, queries, k, nprobe)
    if isinstance(index, IVFFlatIndex):
        return ivf_flat_search(index, queries, k, nprobe)
    raise TypeError(f"unknown ANN index {type(index)}")
