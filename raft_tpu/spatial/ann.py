"""Approximate nearest neighbors: IVF-Flat, IVF-PQ, IVF-SQ — native.

Reference: spatial/knn/ann.hpp:45,71 (``approx_knn_build_index`` /
``approx_knn_search``) with params ``IVFParam``/``IVFPQParam``/``IVFSQParam``
(ann_common.h:42-72).  The reference delegates build+search entirely to
FAISS GPU (detail/ann_quantized_faiss.cuh:75+); the TPU build implements
the quantizers natively (SURVEY.md §7.8):

- **IVF-Flat**: k-means coarse quantizer (reusing spectral/kmeans) +
  slotted per-list storage (below).  Scanning a slot per query step is a
  batched matmul on the MXU, the TPU-shaped substitute for FAISS's
  warp-level list scans.
- **IVF-PQ**: product quantization of residuals (M subspaces × 2^n_bits
  codes, k-means codebooks); search = per-query ADC lookup tables, code
  gathers, segment sums.
- **IVF-SQ**: per-dimension 8-bit scalar quantization of residuals (the
  QT_8bit family) scanned like IVF-Flat after dequantization.

**Slotted list storage.** FAISS keeps variable-length inverted lists
(ann_quantized_faiss.cuh:75); a TPU needs static shapes.  Padding every
list to the *longest* list collapses under skew — one hot cluster
inflates the whole index and every query batch.  Instead, lists are cut
into fixed-length *slots* of ``cap`` rows (cap = mean list size, rounded
up to a multiple of 8): a hot list simply owns several slots.  Total
storage is ≤ n_rows + nlist·cap ≈ 2·n_rows regardless of skew, and
search scans one (n_queries, cap, d) slot at a time inside a
``fori_loop`` instead of materializing (n_queries, nprobe, max_len, d).
Each query's valid slots are compacted to the front of its scan list and
the (traced) trip count is the batch's worst-case live-slot total, so
scan compute tracks the lengths of the lists actually probed — a batch
that avoids the hot list doesn't pay for it.

All searches return (distances, ids) best-first like brute_force_knn.
L2 metrics are supported (reference FAISS path likewise restricts the
metric set, ann_quantized_faiss.cuh:94-118).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.handle import record_on_handle
from raft_tpu.core.profiler import profiled_jit
from raft_tpu.core.utils import round_up_safe
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import expanded_sq_dists
from raft_tpu.spatial.select_k import select_k
from raft_tpu.spectral.kmeans import kmeans

D = DistanceType


# --------------------------------------------------------------------- #
# params (reference ann_common.h:42-72)
# --------------------------------------------------------------------- #
@dataclass
class IVFFlatParams:
    nlist: int
    nprobe: int = 8


@dataclass
class IVFPQParams:
    nlist: int
    nprobe: int = 8
    M: int = 8           # subquantizers
    n_bits: int = 8      # log2 codebook size
    refine_ratio: int = 1  # >1: exact re-rank of top k*ratio candidates


@dataclass
class IVFSQParams:
    nlist: int
    nprobe: int = 8
    qtype: str = "QT_8bit"
    encode_residual: bool = True


class IVFFlatIndex(NamedTuple):
    centroids: jnp.ndarray     # (nlist, d)
    slot_vecs: jnp.ndarray     # (n_slots, cap, d) padded vectors
    slot_ids: jnp.ndarray      # (n_slots, cap) original row ids, -1 pad
    slot_centroid: jnp.ndarray  # (n_slots,) owning list of each slot
    cent_slots: jnp.ndarray    # (nlist, max_slots) slot ids per list, -1 pad
    list_sizes: jnp.ndarray    # (nlist,)
    metric: DistanceType
    nprobe: int                # default probe count from build params
    # (n_slots, cap) precomputed squared norms: computing them in the
    # probe scan forces the gathered (nq, cap, d) slot block to
    # materialize (the einsum alone fuses the gather away) — measured
    # ~10x the whole step's cost on the CPU backend.  Optional only for
    # hand-built legacy tuples; search falls back to an eager compute.
    slot_norms: Optional[jnp.ndarray] = None


class IVFPQIndex(NamedTuple):
    centroids: jnp.ndarray     # (nlist, d) coarse
    codebooks: jnp.ndarray     # (M, ksub, dsub) per-subspace codewords
    slot_codes: jnp.ndarray    # (n_slots, cap, M) codes
    slot_ids: jnp.ndarray      # (n_slots, cap)
    slot_centroid: jnp.ndarray
    cent_slots: jnp.ndarray
    list_sizes: jnp.ndarray
    metric: DistanceType
    nprobe: int
    # refinement (FAISS IndexRefineFlat analog): original vectors kept
    # only when built with refine_ratio > 1, for exact re-ranking of the
    # ADC top-(k*refine_ratio) candidates
    vectors: Optional[jnp.ndarray] = None
    refine_ratio: int = 1


class IVFSQIndex(NamedTuple):
    centroids: jnp.ndarray
    slot_q: jnp.ndarray        # (n_slots, cap, d) quantized residuals
    scale: jnp.ndarray         # (d,) dequant scale
    offset: jnp.ndarray        # (d,) dequant offset
    slot_ids: jnp.ndarray
    slot_centroid: jnp.ndarray
    cent_slots: jnp.ndarray
    list_sizes: jnp.ndarray
    metric: DistanceType
    nprobe: int
    encode_residual: bool      # build-time setting, honored by search


# --------------------------------------------------------------------- #
# shared coarse quantizer plumbing
# --------------------------------------------------------------------- #
@jax.jit
def _assign_chunk_jit(chunk, centroids):
    return jnp.argmin(expanded_sq_dists(chunk, centroids),
                      axis=1).astype(jnp.int32)


def _assign_labels(X, centroids, chunk: int = 131072) -> jnp.ndarray:
    """Nearest-centroid assignment in row chunks: one (chunk, nlist)
    expanded-L2 matmul + argmin per step, so the full pass never
    materializes an (m, nlist) distance matrix for large m."""
    X = jnp.asarray(X)
    outs = [_assign_chunk_jit(X[start:start + chunk], centroids)
            for start in range(0, X.shape[0], chunk)]
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)


def _coarse_assign(X, nlist, seed, train_rows: Optional[int] = None):
    """k-means coarse quantizer + list assignment.

    ``train_rows`` (opt-in) trains k-means on a seeded row subsample and
    assigns ALL rows in one chunked nearest-centroid pass — the FAISS
    ``max_points_per_centroid`` trade: past ~100 training points per
    centroid the Lloyd iterations dominate build time while centroid
    quality has long saturated, so a 1M-row build pays minutes of
    k-means for noise.  ``None`` keeps the historical full-data
    training (bit-identical to prior builds).
    """
    m = X.shape[0]
    if train_rows is not None and train_rows < m:
        expects(train_rows >= nlist,
                "_coarse_assign: train_rows=%d < nlist=%d",
                train_rows, nlist)
        rows = np.sort(np.random.default_rng(seed).choice(
            m, train_rows, replace=False))
        res = kmeans(X[jnp.asarray(rows)], nlist, seed=seed, max_iter=25)
        return res.centroids, _assign_labels(X, res.centroids)
    res = kmeans(X, nlist, seed=seed, max_iter=25)
    return res.centroids, res.labels


def _pack_lists(labels: np.ndarray, nlist: int
                ) -> Tuple[np.ndarray, int]:
    """Host: (nlist, max_len) row-id table, -1 padded.

    Native path: cpp/src/host_runtime.cpp rt_build_lists (the sequential
    packing loop); vectorized numpy fallback below.
    """
    from raft_tpu.core import native

    nat = native.build_lists(labels, nlist)
    if nat is not None:
        return nat
    counts = np.bincount(labels, minlength=nlist)
    max_len = max(int(counts.max()), 1)
    order = np.argsort(labels, kind="stable")
    starts = np.zeros(nlist + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    # position of each sorted row within its list
    within = np.arange(len(labels)) - starts[labels[order]]
    table = np.full((nlist, max_len), -1, np.int64)
    table[labels[order], within] = order
    return table, max_len


def _build_slots(labels: np.ndarray, nlist: int, cap: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int,
                            np.ndarray]:
    """Host: cut each list into fixed-``cap``-length slots (module doc).

    Returns (slot_rows (n_slots, cap) int32 row ids -1-padded,
    slot_centroid (n_slots,) int32, cent_slots (nlist, max_slots) int32
    slot ids -1-padded, cap, counts (nlist,)).
    """
    labels = np.asarray(labels)
    counts = np.bincount(labels, minlength=nlist)
    max_count = max(int(counts.max()), 1)
    if cap is None:
        # cap ≈ mean list size: total storage Σ ceil(cᵢ/cap)·cap is then
        # ≤ m + nlist·cap ≈ 2m whatever the skew (a quantile cap fails
        # this when k-means leaves a long tail of small lists)
        mean = -(-len(labels) // nlist)
        cap = min(max_count, max(8, round_up_safe(mean, 8)))
    table, max_len = _pack_lists(labels, nlist)
    slots_per = -(-counts // cap)       # ceildiv; empty lists get 0 slots
    max_slots = max(int(slots_per.max()), 1)
    n_slots = int(slots_per.sum())
    # pad the table width to a whole number of slots, then cut
    tab = np.full((nlist, max_slots * cap), -1, np.int64)
    tab[:, :max_len] = table
    mask = np.arange(max_slots)[None, :] < slots_per[:, None]
    slot_rows = tab.reshape(nlist, max_slots, cap)[mask]
    slot_centroid = np.repeat(
        np.arange(nlist, dtype=np.int32), slots_per).astype(np.int32)
    cent_slots = np.full((nlist, max_slots), -1, np.int32)
    cent_slots[mask] = np.arange(n_slots, dtype=np.int32)
    return slot_rows.astype(np.int32), slot_centroid, cent_slots, cap, counts


_L2_METRICS = (D.L2Expanded, D.L2SqrtExpanded, D.L2Unexpanded,
               D.L2SqrtUnexpanded)


def _check_metric(name, metric):
    expects(metric in _L2_METRICS,
            "%s: unsupported metric %d — the IVF quantizers are L2-only "
            "(the reference FAISS path likewise restricts the metric set, "
            "ann_quantized_faiss.cuh:94-118)", name, int(metric))


# entry points that already warned about an over-nlist nprobe clamp (the
# warning is one-time per entry point: a serving loop probing at a
# clamped count must not spam a warning per batch)
_NPROBE_CLAMP_WARNED = set()


def _validate_nprobe(name: str, nprobe, nlist: int) -> int:
    """Validate and resolve a probe count at the public entry points.

    A non-positive ``nprobe`` is a caller bug and raises
    :class:`~raft_tpu.core.error.LogicError`; ``nprobe > nlist`` is
    clamped to ``nlist`` with a one-time warning (probing every list is
    well-defined — a full scan — but almost always a mis-sized knob, and
    silently passing the oversized count into the probe scan would bake
    garbage probe ranks into the compiled program's shape).
    """
    nprobe = int(nprobe)
    expects(nprobe >= 1, "%s: nprobe must be >= 1, got %d", name, nprobe)
    if nprobe > nlist:
        if name not in _NPROBE_CLAMP_WARNED:
            _NPROBE_CLAMP_WARNED.add(name)
            warnings.warn(
                "%s: nprobe=%d exceeds nlist=%d; clamping to nlist "
                "(reported once per entry point)" % (name, nprobe, nlist),
                stacklevel=3)
        nprobe = nlist
    return nprobe


def _probe_compact(q, centroids, cent_slots, nprobe, select_impl=None,
                   probes=None):
    """Probe selection + valid-first scan-list compaction — the shared
    front half of every IVF search path (the XLA fori-loop scan AND the
    fused Pallas kernel consume the SAME ``slots``/``prank`` arrays, so
    probe tie order can never differ between them).

    Returns ``(slots (nq, nprobe*max_slots) int32 valid-first
    -1-padded, prank (same shape) probe ranks, n_live traced
    worst-case live-slot count)``.
    """
    nq = q.shape[0]
    nlist, max_slots = cent_slots.shape
    nprobe = min(nprobe, nlist)
    if probes is None:
        qc = expanded_sq_dists(q, centroids)
        _, probes = select_k(qc, nprobe, select_min=True,
                             impl=select_impl)               # (nq, nprobe)
    slots = cent_slots[probes].reshape(nq, -1)               # -1-padded
    prank = jnp.broadcast_to(
        jnp.repeat(jnp.arange(nprobe, dtype=jnp.int32), max_slots)[None],
        slots.shape)
    # valid-first compaction as ONE stable variadic sort (slots/prank
    # ride as operands) — argsort + two take_along_axis would be serial
    # per-row gathers on TPU (r4 tile-merge finding)
    _, slots, prank = lax.sort(
        ((slots < 0).astype(jnp.int32), slots, prank), dimension=1,
        num_keys=1, is_stable=True)
    n_live = jnp.max(jnp.sum(slots >= 0, axis=1))
    return slots, prank, n_live


def _probe_scan_search(q, centroids, cent_slots, step_dist, k, nprobe,
                       metric, probes=None, select_impl=None):
    """Shared IVF search driver: probe centroids, then scan the probed
    lists' slots one at a time with a running top-k.

    ``step_dist(slx, pjx) -> (dist (nq, cap), ids (nq, cap))`` computes
    one slot's candidate distances given per-query slot ids ``slx`` and
    the per-query probe rank ``pjx`` each slot belongs to (so per-probe
    precomputes — the PQ ADC tables — can be gathered, not rebuilt).
    When the caller has already selected probe lists (to build such
    precomputes), it passes the (nq, nprobe) ``probes`` array and the
    scan derives from that SAME selection — probe ranks and per-probe
    tables can never disagree on tie order.
    The fori_loop keeps the live set at (nq, cap, d) — never
    (nq, nprobe, max_len, d) — and HLO size O(1) in the probe count.
    Valid slots are compacted to the front of each query's scan list and
    the (traced) trip count is the batch's worst-case live-slot count,
    so scan cost tracks the lengths of the lists actually probed, not
    nprobe·max_slots.
    """
    nq = q.shape[0]
    slots, prank, n_live = _probe_compact(q, centroids, cent_slots,
                                          nprobe, select_impl, probes)

    dt = jnp.result_type(q.dtype, jnp.float32)
    init = (jnp.full((nq, k), jnp.inf, dt),
            jnp.full((nq, k), -1, jnp.int32))

    def body(j, carry):
        run_d, run_i = carry
        sl = slots[:, j]
        valid = sl >= 0
        slx = jnp.where(valid, sl, 0)
        dist, ids = step_dist(slx, prank[:, j])
        ids = jnp.where(valid[:, None], ids, -1)
        dist = jnp.where(ids >= 0, jnp.maximum(dist, 0.0),
                         jnp.inf).astype(dt)
        cat_d = jnp.concatenate([run_d, dist], axis=1)
        cat_i = jnp.concatenate([run_i, ids], axis=1)
        return select_k(cat_d, k, select_min=True, values=cat_i,
                        impl=select_impl)

    dist, ids = lax.fori_loop(0, n_live, body, init)
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(dist)
    return dist, ids


# --------------------------------------------------------------------- #
# delta segment: streaming-ingestion merge (docs/SERVING.md)
# --------------------------------------------------------------------- #
def _delta_merge_impl(delta_vecs, delta_ids, base_d, base_i, q, k, sqrt):
    """Brute-force scan of an append-only delta segment merged into a
    base (IVF) result stream.

    ``delta_ids < 0`` marks unfilled capacity rows — their distances are
    forced to ``+inf`` so they can never displace a real candidate, and
    the segment keeps ONE static shape however full it is (a growing
    delta must not retrace the serving executables).  Base entries ride
    first in the concatenation, so on exact ties the stable top-k keeps
    the base copy — results are deterministic across a compaction swap
    that migrates a row from delta to base storage.
    """
    qn = jnp.sum(q * q, axis=1)
    dn = jnp.sum(delta_vecs * delta_vecs, axis=1)
    dist = (qn[:, None] + dn[None, :]
            - 2.0 * jnp.einsum("nd,cd->nc", q, delta_vecs,
                               precision="highest"))
    valid = delta_ids >= 0
    dist = jnp.where(valid[None, :], jnp.maximum(dist, 0.0),
                     jnp.inf).astype(base_d.dtype)
    if sqrt:
        # the base stream already carries sqrted distances (the search
        # applies the metric's sqrt before returning) — match it so the
        # merged keys are commensurable
        dist = jnp.sqrt(dist)
    ids = jnp.broadcast_to(
        jnp.where(valid, delta_ids, -1).astype(jnp.int32)[None, :],
        dist.shape)
    cat_d = jnp.concatenate([base_d, dist], axis=1)
    cat_i = jnp.concatenate([base_i.astype(jnp.int32), ids], axis=1)
    # the base-first tie rule above IS the determinism-across-swap
    # contract, and only the stable "topk" payload select honors tie
    # order — so this one select is pinned regardless of the caller's
    # select_impl (which still speeds the per-step probe scans).  Cost:
    # one (nq, k + delta_cap) sort per batch, only on the delta arm.
    return select_k(cat_d, k, select_min=True, values=cat_i,
                    impl="topk")


_DELTA_STATICS = ("k", "sqrt")
_delta_merge_jit = profiled_jit(
    name="ann_delta_merge",
    static_argnames=_DELTA_STATICS)(_delta_merge_impl)
# donating twin (docs/ZERO_COPY.md): a separate wrapper, not a flag — a
# donating and a non-donating executable must never share a cache slot
_delta_merge_jit_donated = profiled_jit(
    name="ann_delta_merge_donated", static_argnames=_DELTA_STATICS,
    donate_argnames=("q",))(_delta_merge_impl)


def _merge_delta(out, delta, q, k, metric, donate_queries):
    """Apply the delta-segment merge to a base search result (shared by
    the three quantizer entry points)."""
    delta_vecs, delta_ids = delta
    delta_vecs = jnp.asarray(delta_vecs)
    delta_ids = jnp.asarray(delta_ids, jnp.int32)
    expects(delta_vecs.ndim == 2 and delta_vecs.shape[1] == q.shape[1],
            "ann delta segment: expected (rows, %d) vectors, got %r",
            q.shape[1], tuple(delta_vecs.shape))
    expects(delta_ids.shape == (delta_vecs.shape[0],),
            "ann delta segment: ids shape %r does not match %d rows",
            tuple(delta_ids.shape), delta_vecs.shape[0])
    sqrt = metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded)
    fn = _delta_merge_jit_donated if donate_queries else _delta_merge_jit
    return fn(delta_vecs, delta_ids, out[0], out[1], q, k, sqrt)


# --------------------------------------------------------------------- #
# IVF-Flat
# --------------------------------------------------------------------- #
def ivf_flat_build(X, params: IVFFlatParams,
                   metric: DistanceType = D.L2Expanded,
                   seed: int = 1234, handle=None,
                   train_rows: Optional[int] = None) -> IVFFlatIndex:
    """Build an IVF-Flat index (reference approx_knn_build_index IVFFlat
    path, ann_quantized_faiss.cuh:129-141).  ``train_rows`` opts into
    subsampled k-means training (:func:`_coarse_assign`)."""
    X = jnp.asarray(X)
    m, d = X.shape
    expects(params.nlist <= m, "ivf_flat_build: nlist > n_vectors")
    _check_metric("ivf_flat_build", metric)
    centroids, labels = _coarse_assign(X, params.nlist, seed, train_rows)
    slot_rows, slot_cent, cent_slots, _, counts = _build_slots(
        np.asarray(labels), params.nlist)
    rows_j = jnp.asarray(slot_rows)
    gather = jnp.where(rows_j >= 0, rows_j, 0)
    slot_vecs = X[gather] * (rows_j >= 0)[..., None]
    idx = IVFFlatIndex(centroids, slot_vecs, rows_j, jnp.asarray(slot_cent),
                       jnp.asarray(cent_slots),
                       jnp.asarray(counts, jnp.int32), metric, params.nprobe,
                       slot_norms=jnp.sum(slot_vecs * slot_vecs, -1))
    record_on_handle(handle, slot_vecs)
    return idx


def _metric_family(metric) -> str:
    """The registry-legality metric string for an IVF DistanceType
    (the quantizers are L2-only, so this is a two-way split)."""
    return ("l2sqrt" if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded)
            else "l2")


def _ivf_flat_search_impl(centroids, slot_vecs, slot_norms, slot_ids,
                          cent_slots, q, k, nprobe, metric,
                          select_impl=None, scan_impl=None):
    # scan-path resolution (override → configure → env → table →
    # auto "xla"): the fused Pallas kernel streams slot tiles through
    # VMEM with a running top-k (ops/ivf_tile.py — no materialized
    # (nq, cap, d) gather block); "xla" is the reference gather+einsum+
    # select oracle below.  Resolved at trace time like select_impl —
    # the executable-cache caveat (config.py module doc) applies.
    scan_impl = tuning.resolve(
        "ivf_scan_impl", scan_impl, site="ivf_flat_search",
        n=slot_vecs.shape[0] * slot_vecs.shape[1], k=k, d=q.shape[1],
        metric=_metric_family(metric), dtype=q.dtype) or "xla"
    if scan_impl in ("pallas", "pallas_bf16"):
        from raft_tpu.ops.ivf_tile import fused_ivf_scan

        slots, _prank, _n_live = _probe_compact(
            q, centroids, cent_slots,
            min(nprobe, cent_slots.shape[0]), select_impl)
        dist, ids = fused_ivf_scan(
            q, slot_vecs, slot_norms, slot_ids, slots, k,
            accum_bf16=(scan_impl == "pallas_bf16"))
        if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
            dist = jnp.sqrt(dist)
        return dist, ids

    qn = jnp.sum(q * q, axis=1)

    def step_dist(slx, _pjx):
        vecs = slot_vecs[slx]                         # (nq, cap, d)
        ids = slot_ids[slx]                           # (nq, cap)
        # precomputed slot norms: the gathered vecs block then feeds
        # ONLY the einsum, which fuses the gather away instead of
        # materializing (nq, cap, d) (the index-field comment)
        dist = (qn[:, None] + slot_norms[slx]
                - 2.0 * jnp.einsum("nd,ncd->nc", q, vecs,
                                   precision="highest"))
        return dist, ids

    return _probe_scan_search(q, centroids, cent_slots, step_dist, k,
                              nprobe, metric, select_impl=select_impl)


# profiled_jit (not bare jax.jit): the serving layer's warmup proof and
# loadgen's post-warmup-compile count read compile_cache_stats(), so the
# programs ANNService fronts must attribute their compiles there like
# every other served primitive (tiled_knn, serve_pairwise)
_IVF_FLAT_STATICS = ("k", "nprobe", "metric", "select_impl",
                     "scan_impl")
_ivf_flat_search_jit = profiled_jit(
    name="ivf_flat_search",
    static_argnames=_IVF_FLAT_STATICS)(_ivf_flat_search_impl)
_ivf_flat_search_jit_donated = profiled_jit(
    name="ivf_flat_search_donated", static_argnames=_IVF_FLAT_STATICS,
    donate_argnames=("q",))(_ivf_flat_search_impl)


def ivf_flat_search(index: IVFFlatIndex, queries, k: int,
                    nprobe: Optional[int] = None, handle=None, *,
                    delta=None, donate_queries: bool = False,
                    select_impl: Optional[str] = None,
                    scan_impl: Optional[str] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search an IVF-Flat index (reference approx_knn_search, ann.hpp:71);
    ``nprobe`` defaults to the build params' value.

    ``delta=(vectors, ids)`` merges an append-only delta segment into
    the result stream (:func:`_delta_merge_impl`); ``donate_queries``
    donates the query buffer to the LAST program that consumes it
    (docs/ZERO_COPY.md) — callers must not reuse ``queries`` after a
    donating call.  ``select_impl`` pins the per-step top-k
    implementation explicitly (None = the ``select_impl`` knob;
    ``"approx"`` is membership-exact at recall 1.0 and measured ~7x
    faster than the full-sort payload path at k=100 on the CPU
    backend, at the cost of tie order).  ``scan_impl`` pins the probe
    scan path: ``"xla"`` (gather+einsum+select oracle), ``"pallas"``
    (the fused one-pass slot-streaming kernel, ops/ivf_tile.py) or
    ``"pallas_bf16"`` (bf16 multiplicands, f32 accumulate); None =
    the ``ivf_scan_impl`` knob (auto "xla" until the TPU table lands).
    """
    q = jnp.asarray(queries)
    nprobe = index.nprobe if nprobe is None else nprobe
    nprobe = _validate_nprobe("ivf_flat_search", nprobe,
                              int(index.centroids.shape[0]))
    metric = DistanceType(int(index.metric))
    norms = index.slot_norms
    if norms is None:   # hand-built legacy tuple: eager fallback
        norms = jnp.sum(index.slot_vecs * index.slot_vecs, -1)
    base_fn = (_ivf_flat_search_jit_donated
               if donate_queries and delta is None
               else _ivf_flat_search_jit)
    out = base_fn(index.centroids, index.slot_vecs, norms,
                  index.slot_ids, index.cent_slots, q, k, nprobe,
                  metric, select_impl=select_impl, scan_impl=scan_impl)
    if delta is not None:
        out = _merge_delta(out, delta, q, k, metric, donate_queries)
    record_on_handle(handle, *out)
    return out


# --------------------------------------------------------------------- #
# IVF-PQ
# --------------------------------------------------------------------- #
def ivf_pq_build(X, params: IVFPQParams,
                 metric: DistanceType = D.L2Expanded,
                 seed: int = 1234, handle=None,
                 train_rows: Optional[int] = None) -> IVFPQIndex:
    """Build IVF-PQ: coarse quantize, then per-subspace k-means codebooks
    over residuals (reference IVFPQ path, ann_quantized_faiss.cuh:143-160)."""
    X = jnp.asarray(X)
    m, d = X.shape
    M, ksub = params.M, 2 ** params.n_bits
    expects(d % M == 0, "ivf_pq_build: dim %d not divisible by M=%d", d, M)
    _check_metric("ivf_pq_build", metric)
    dsub = d // M
    centroids, labels = _coarse_assign(X, params.nlist, seed, train_rows)
    resid = X - centroids[labels]

    codebooks = []
    codes_flat = []
    for mi in range(M):
        sub = resid[:, mi * dsub:(mi + 1) * dsub]
        kk = min(ksub, m)
        res = kmeans(sub, kk, seed=seed + mi, max_iter=20)
        cb = res.centroids
        if kk < ksub:  # pad codebook
            cb = jnp.concatenate(
                [cb, jnp.full((ksub - kk, dsub), jnp.inf, cb.dtype)])
        codebooks.append(cb)
        codes_flat.append(res.labels)
    codebooks = jnp.stack(codebooks)                  # (M, ksub, dsub)
    codes_flat = jnp.stack(codes_flat, axis=1)        # (m, M)

    slot_rows, slot_cent, cent_slots, _, counts = _build_slots(
        np.asarray(labels), params.nlist)
    rows_j = jnp.asarray(slot_rows)
    gather = jnp.where(rows_j >= 0, rows_j, 0)
    slot_codes = codes_flat[gather]                   # (n_slots, cap, M)
    ratio = max(int(params.refine_ratio), 1)
    idx = IVFPQIndex(centroids, codebooks, slot_codes, rows_j,
                     jnp.asarray(slot_cent), jnp.asarray(cent_slots),
                     jnp.asarray(counts, jnp.int32), metric, params.nprobe,
                     vectors=X if ratio > 1 else None, refine_ratio=ratio)
    record_on_handle(handle, slot_codes)
    return idx


def _ivf_pq_search_impl(centroids, codebooks, slot_codes, slot_ids,
                        slot_centroid, cent_slots, q, k, nprobe, metric,
                        adc="gather", select_impl=None):
    M, ksub, dsub = codebooks.shape
    nlist = centroids.shape[0]
    nq = q.shape[0]
    cb_norms = jnp.sum(codebooks * codebooks, -1)      # (M, ksub)

    # ADC lookup tables depend only on the probed list (residual =
    # q - centroid): build them once per probe, BEFORE the slot loop.
    # The SAME probes array is handed to _probe_scan_search so the
    # prank -> LUT pairing holds even when the selection impl has
    # unguaranteed tie order (approx_max_k).
    np_eff = min(nprobe, nlist)
    qc = expanded_sq_dists(q, centroids)
    _, probes = select_k(qc, np_eff, select_min=True,
                         impl=select_impl)              # (nq, np_eff)
    resid = q[:, None, :] - centroids[probes]           # (nq, np_eff, d)
    rs = resid.reshape(nq, np_eff, M, dsub)
    lut_all = (jnp.sum(rs * rs, -1)[..., None] + cb_norms[None, None]
               - 2.0 * jnp.einsum("npmd,mkd->npmk", rs, codebooks,
                                  precision="highest"))  # (nq,np,M,ksub)
    if adc == "onehot":
        # padded codebook entries (build pads short codebooks with inf
        # rows) make their LUT slots inf; the gather path never reads
        # them, but the one-hot einsum would turn 0 * inf into NaN —
        # sanitize ONCE, outside the slot scan (codes never reference
        # padded slots, so a zeroed slot contributes exactly nothing)
        lut_all = jnp.where(jnp.isfinite(lut_all), lut_all, 0.0)

    def step_dist(slx, pjx):
        lut = lut_all[jnp.arange(nq), pjx]             # (nq, M, ksub)
        codes = slot_codes[slx]                        # (nq, cap, M)
        if adc == "onehot":
            # LUT lookup as one-hot contractions: dist[n,c] =
            # sum_m lut[n,m,codes[n,c,m]] = sum_m onehot(codes_m) .
            # lut_m.  256x the FLOPs of the gather but fully
            # vector/MXU-shaped, vs a per-element serial gather — the
            # same trade as the kNN merge rewrite (tiled_knn.py); the
            # bench compares both on hardware.  Static per-m loop keeps
            # the one-hot transient at (nq, cap, ksub).
            # (lut_all was inf-sanitized above, once, outside the scan)
            dist = jnp.zeros(codes.shape[:2], lut.dtype)
            for m in range(M):
                oh = jax.nn.one_hot(codes[:, :, m].astype(jnp.int32),
                                    ksub, dtype=lut.dtype)
                dist = dist + jnp.einsum("nck,nk->nc", oh,
                                         lut[:, m, :],
                                         precision="highest")
        else:
            codes_t = jnp.transpose(codes, (0, 2, 1)).astype(jnp.int32)
            dist = jnp.sum(jnp.take_along_axis(lut, codes_t, axis=-1),
                           axis=1)
        return dist, slot_ids[slx]

    return _probe_scan_search(q, centroids, cent_slots, step_dist, k,
                              nprobe, metric, probes=probes,
                              select_impl=select_impl)


_IVF_PQ_STATICS = ("k", "nprobe", "metric", "adc", "select_impl")
_ivf_pq_search_jit = profiled_jit(
    name="ivf_pq_search",
    static_argnames=_IVF_PQ_STATICS)(_ivf_pq_search_impl)
_ivf_pq_search_jit_donated = profiled_jit(
    name="ivf_pq_search_donated", static_argnames=_IVF_PQ_STATICS,
    donate_argnames=("q",))(_ivf_pq_search_impl)


def _refine_impl(vectors, q, cand_ids, k, sqrt):
    """Exact re-rank of ADC candidates against the original vectors
    (the quality half of FAISS's IndexRefineFlat, which the reference
    inherits via ann_quantized_faiss.cuh:75)."""
    valid = cand_ids >= 0
    vecs = vectors[jnp.where(valid, cand_ids, 0)]      # (nq, k2, d)
    diff = vecs - q[:, None, :]
    dist = jnp.sum(diff * diff, axis=-1)
    dist = jnp.where(valid, dist, jnp.inf)
    out_d, out_i = select_k(dist, k, select_min=True,
                            values=cand_ids)
    if sqrt:
        out_d = jnp.sqrt(out_d)
    return out_d, out_i


_REFINE_STATICS = ("k", "sqrt")
_refine_jit = profiled_jit(
    name="ivf_pq_refine", static_argnames=_REFINE_STATICS)(_refine_impl)
_refine_jit_donated = profiled_jit(
    name="ivf_pq_refine_donated", static_argnames=_REFINE_STATICS,
    donate_argnames=("q",))(_refine_impl)


def ivf_pq_search(index: IVFPQIndex, queries, k: int,
                  nprobe: Optional[int] = None,
                  refine_ratio: Optional[int] = None, handle=None, *,
                  delta=None, donate_queries: bool = False,
                  select_impl: Optional[str] = None):
    """ADC search; when the index holds original vectors and
    ``refine_ratio`` (default: build-time value) is > 1, the top
    ``k*refine_ratio`` ADC candidates are re-ranked exactly.
    ``delta`` / ``donate_queries`` as in :func:`ivf_flat_search`; the
    query buffer is donated only to the LAST stage that consumes it
    (ADC scan → refine → delta merge)."""
    q = jnp.asarray(queries)
    nprobe = index.nprobe if nprobe is None else nprobe
    nprobe = _validate_nprobe("ivf_pq_search", nprobe,
                              int(index.centroids.shape[0]))
    ratio = index.refine_ratio if refine_ratio is None else refine_ratio
    ratio = max(int(ratio), 1)
    refine = ratio > 1 and index.vectors is not None
    metric = DistanceType(int(index.metric))
    k_search = k * ratio if refine else k
    # ADC impl resolved at CALL time through the candidate registry (a
    # trace-time env read would pin the first value into the
    # shape-keyed executable cache — the select_k caveat)
    adc = tuning.resolve("pq_adc", None, site="ivf_pq_search",
                         n=int(index.slot_ids.shape[0]
                               * index.slot_ids.shape[1]),
                         k=k, dtype=q.dtype)
    base_fn = (_ivf_pq_search_jit_donated
               if donate_queries and not refine and delta is None
               else _ivf_pq_search_jit)
    out = base_fn(index.centroids, index.codebooks,
                  index.slot_codes, index.slot_ids,
                  index.slot_centroid, index.cent_slots,
                  q, k_search, nprobe, metric, adc=adc,
                  select_impl=select_impl)
    if refine:
        sqrt = metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded)
        refine_fn = (_refine_jit_donated
                     if donate_queries and delta is None else _refine_jit)
        out = refine_fn(index.vectors, q, out[1], k, sqrt)
    if delta is not None:
        out = _merge_delta(out, delta, q, k, metric, donate_queries)
    record_on_handle(handle, *out)
    return out


# --------------------------------------------------------------------- #
# IVF-SQ
# --------------------------------------------------------------------- #
def ivf_sq_build(X, params: IVFSQParams,
                 metric: DistanceType = D.L2Expanded,
                 seed: int = 1234, handle=None,
                 train_rows: Optional[int] = None) -> IVFSQIndex:
    """8-bit scalar quantization of residuals (QT_8bit; reference IVFSQ
    path, ann_quantized_faiss.cuh:162-176)."""
    expects(params.qtype in ("QT_8bit", "QT_8bit_uniform"),
            "ivf_sq_build: unsupported qtype %s", params.qtype)
    _check_metric("ivf_sq_build", metric)
    X = jnp.asarray(X)
    centroids, labels = _coarse_assign(X, params.nlist, seed, train_rows)
    resid = X - centroids[labels] if params.encode_residual else X
    lo = jnp.min(resid, axis=0)
    hi = jnp.max(resid, axis=0)
    if params.qtype == "QT_8bit_uniform":
        lo = jnp.full_like(lo, jnp.min(lo))
        hi = jnp.full_like(hi, jnp.max(hi))
    scale = (hi - lo) / 255.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q_all = jnp.clip(jnp.round((resid - lo) / scale), 0, 255).astype(jnp.uint8)

    slot_rows, slot_cent, cent_slots, _, counts = _build_slots(
        np.asarray(labels), params.nlist)
    rows_j = jnp.asarray(slot_rows)
    gather = jnp.where(rows_j >= 0, rows_j, 0)
    slot_q = q_all[gather]
    idx = IVFSQIndex(centroids, slot_q, scale, lo, rows_j,
                     jnp.asarray(slot_cent), jnp.asarray(cent_slots),
                     jnp.asarray(counts, jnp.int32), metric, params.nprobe,
                     params.encode_residual)
    record_on_handle(handle, slot_q)
    return idx


def _ivf_sq_search_impl(centroids, slot_q, scale, offset, slot_ids,
                        slot_centroid, cent_slots, q, k, nprobe,
                        encode_residual, metric, select_impl=None):
    qn = jnp.sum(q * q, axis=1)

    def step_dist(slx, _pjx):
        # dequantize only the live slot (the whole store stays uint8 in
        # HBM — the memory point of scalar quantization)
        deq = slot_q[slx].astype(jnp.float32) * scale + offset
        if encode_residual:
            deq = deq + centroids[slot_centroid[slx]][:, None, :]
        dist = (qn[:, None] + jnp.sum(deq * deq, -1)
                - 2.0 * jnp.einsum("nd,ncd->nc", q, deq,
                                   precision="highest"))
        return dist, slot_ids[slx]

    return _probe_scan_search(q, centroids, cent_slots, step_dist, k,
                              nprobe, metric, select_impl=select_impl)


_IVF_SQ_STATICS = ("k", "nprobe", "encode_residual", "metric",
                   "select_impl")
_ivf_sq_search_jit = profiled_jit(
    name="ivf_sq_search",
    static_argnames=_IVF_SQ_STATICS)(_ivf_sq_search_impl)
_ivf_sq_search_jit_donated = profiled_jit(
    name="ivf_sq_search_donated", static_argnames=_IVF_SQ_STATICS,
    donate_argnames=("q",))(_ivf_sq_search_impl)


def ivf_sq_search(index: IVFSQIndex, queries, k: int,
                  nprobe: Optional[int] = None, handle=None, *,
                  delta=None, donate_queries: bool = False,
                  select_impl: Optional[str] = None):
    """Search; honors the build-time ``encode_residual`` setting.
    ``delta`` / ``donate_queries`` / ``select_impl`` as in
    :func:`ivf_flat_search`."""
    q = jnp.asarray(queries)
    nprobe = index.nprobe if nprobe is None else nprobe
    nprobe = _validate_nprobe("ivf_sq_search", nprobe,
                              int(index.centroids.shape[0]))
    base_fn = (_ivf_sq_search_jit_donated
               if donate_queries and delta is None
               else _ivf_sq_search_jit)
    out = base_fn(index.centroids, index.slot_q, index.scale,
                  index.offset, index.slot_ids,
                  index.slot_centroid, index.cent_slots,
                  q, k, nprobe, bool(index.encode_residual),
                  DistanceType(int(index.metric)),
                  select_impl=select_impl)
    if delta is not None:
        out = _merge_delta(out, delta, q, k,
                           DistanceType(int(index.metric)),
                           donate_queries)
    record_on_handle(handle, *out)
    return out


# --------------------------------------------------------------------- #
# streaming ingestion: reconstruction + compaction (docs/SERVING.md)
# --------------------------------------------------------------------- #
def ivf_flat_reconstruct(index: IVFFlatIndex
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Recover the stored ``(vectors, ids)`` from slot storage (valid
    rows only, slot order).  The exact inverse of the build gather —
    IVF-Flat stores raw vectors, so reconstruction is lossless."""
    ids = np.asarray(index.slot_ids).reshape(-1)
    mask = ids >= 0
    vecs = np.asarray(index.slot_vecs).reshape(
        -1, index.slot_vecs.shape[-1])
    return vecs[mask], ids[mask].astype(np.int64)


def _extend_slot_layout(labels: np.ndarray, nlist: int, cap: int,
                        slot_multiple: int
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                   np.ndarray]:
    """Shared host-side slot layout for the extend paths (resident
    :func:`ivf_flat_extend` and the out-of-core
    :func:`raft_tpu.spatial.ooc.ooc_extend`): cut the labeled rows into
    ``cap``-length slots, then round the slot count (and the per-list
    table width, to a multiple of 8) UP to ``slot_multiple`` so repeat
    compactions that stay inside the rounded shape reuse the compiled
    search executables.  Returns ``(slot_rows, slot_cent, cent_slots,
    counts)`` — all numpy; padding slots hold ids=-1 and are never
    referenced by ``cent_slots``."""
    expects(slot_multiple >= 1, "_extend_slot_layout: slot_multiple=%d",
            slot_multiple)
    slot_rows, slot_cent, cent_slots, _, counts = _build_slots(
        labels, nlist, cap=cap)
    n_slots = slot_rows.shape[0]
    pad_slots = round_up_safe(max(n_slots, 1), slot_multiple) - n_slots
    if pad_slots:
        slot_rows = np.concatenate(
            [slot_rows, np.full((pad_slots, cap), -1, slot_rows.dtype)])
        slot_cent = np.concatenate(
            [slot_cent, np.zeros(pad_slots, slot_cent.dtype)])
    max_slots = cent_slots.shape[1]
    pad_width = round_up_safe(max(max_slots, 1), 8) - max_slots
    if pad_width:
        cent_slots = np.concatenate(
            [cent_slots, np.full((nlist, pad_width), -1,
                                 cent_slots.dtype)], axis=1)
    return slot_rows, slot_cent, cent_slots, counts


def ivf_flat_extend(index: IVFFlatIndex, vectors, ids, *,
                    slot_multiple: int = 64,
                    handle=None) -> IVFFlatIndex:
    """Fold new rows into an existing IVF-Flat index WITHOUT re-running
    k-means: assign each new vector to its nearest existing centroid,
    then rebuild the slotted storage over old + new rows — the
    compaction half of streaming ingestion (docs/SERVING.md).

    Centroids, metric, default nprobe and slot ``cap`` are preserved;
    ``slot_ids`` carry the caller's global id space (the existing
    index's ids plus ``ids``; keeping them collision-free is the
    caller's contract).  ``slot_multiple`` rounds the rebuilt slot count
    (and the per-list slot-table width, to a multiple of 8) UP, so
    successive compactions that stay inside the rounded shape reuse the
    compiled search executables instead of paying one recompile per
    compaction — padding slots are never referenced by ``cent_slots``
    and cost no scan time (the probe scan is compacted valid-first).
    """
    expects(slot_multiple >= 1, "ivf_flat_extend: slot_multiple=%d",
            slot_multiple)
    new_vecs = jnp.asarray(vectors)
    expects(new_vecs.ndim == 2
            and new_vecs.shape[1] == index.centroids.shape[1],
            "ivf_flat_extend: expected (rows, %d) vectors, got %r",
            int(index.centroids.shape[1]), tuple(new_vecs.shape))
    new_ids = np.asarray(ids, np.int64).ravel()
    expects(new_ids.shape[0] == new_vecs.shape[0],
            "ivf_flat_extend: %d ids for %d vectors",
            new_ids.shape[0], new_vecs.shape[0])
    nlist = int(index.centroids.shape[0])
    cap = int(index.slot_vecs.shape[1])

    old_vecs, old_ids = ivf_flat_reconstruct(index)
    old_labels = np.repeat(np.asarray(index.slot_centroid), cap)[
        np.asarray(index.slot_ids).reshape(-1) >= 0]
    if new_vecs.shape[0]:
        new_labels = np.asarray(_assign_labels(new_vecs,
                                               index.centroids))
        all_vecs = np.concatenate(
            [old_vecs, np.asarray(new_vecs, old_vecs.dtype)], axis=0)
        all_ids = np.concatenate([old_ids, new_ids])
        labels = np.concatenate(
            [old_labels.astype(np.int64), new_labels.astype(np.int64)])
    else:
        all_vecs, all_ids = old_vecs, old_ids
        labels = old_labels.astype(np.int64)

    # shape-stability padding (inside _extend_slot_layout): extra slots
    # hold ids=-1 / zero vectors and no cent_slots entry points at them
    slot_rows, slot_cent, cent_slots, counts = _extend_slot_layout(
        labels, nlist, cap, slot_multiple)

    rows_j = jnp.asarray(slot_rows)
    gather = jnp.where(rows_j >= 0, rows_j, 0)
    all_v = jnp.asarray(all_vecs)
    slot_vecs = all_v[gather] * (rows_j >= 0)[..., None]
    slot_ids = jnp.where(rows_j >= 0,
                         jnp.asarray(all_ids, jnp.int32)[gather], -1)
    out = IVFFlatIndex(index.centroids, slot_vecs, slot_ids,
                       jnp.asarray(slot_cent), jnp.asarray(cent_slots),
                       jnp.asarray(counts, jnp.int32), index.metric,
                       index.nprobe,
                       slot_norms=jnp.sum(slot_vecs * slot_vecs, -1))
    record_on_handle(handle, slot_vecs)
    return out


# --------------------------------------------------------------------- #
# dispatcher (reference ann.hpp:45,71)
# --------------------------------------------------------------------- #
def approx_knn_build_index(X, params, metric: DistanceType = D.L2Expanded,
                           seed: int = 1234, handle=None,
                           train_rows: Optional[int] = None):
    if isinstance(params, IVFPQParams):
        return ivf_pq_build(X, params, metric, seed, handle=handle,
                            train_rows=train_rows)
    if isinstance(params, IVFSQParams):
        return ivf_sq_build(X, params, metric, seed, handle=handle,
                            train_rows=train_rows)
    if isinstance(params, IVFFlatParams):
        return ivf_flat_build(X, params, metric, seed, handle=handle,
                              train_rows=train_rows)
    raise TypeError(f"unknown ANN params {type(params)}")


def approx_knn_search(index, queries, k: int, nprobe: Optional[int] = None,
                      refine_ratio: Optional[int] = None, handle=None, *,
                      delta=None, donate_queries: bool = False,
                      select_impl: Optional[str] = None):
    """Dispatch by index type; ``delta=(vectors, ids)`` merges an
    append-only delta segment into the result stream,
    ``donate_queries`` donates the query buffer to its last consumer,
    and ``select_impl`` pins the top-k implementation
    (see :func:`ivf_flat_search`)."""
    if isinstance(index, IVFPQIndex):
        return ivf_pq_search(index, queries, k, nprobe,
                             refine_ratio=refine_ratio, handle=handle,
                             delta=delta, donate_queries=donate_queries,
                             select_impl=select_impl)
    if isinstance(index, IVFSQIndex):
        return ivf_sq_search(index, queries, k, nprobe, handle=handle,
                             delta=delta, donate_queries=donate_queries,
                             select_impl=select_impl)
    if isinstance(index, IVFFlatIndex):
        return ivf_flat_search(index, queries, k, nprobe, handle=handle,
                               delta=delta, donate_queries=donate_queries,
                               select_impl=select_impl)
    raise TypeError(f"unknown ANN index {type(index)}")
