"""Multi-node multi-device kNN over a mesh axis — the sharded SPMD search.

Reference: the MNMG mode of ``brute_force_knn`` — each rank searches its
row partition of the index locally, then results are merged through the
injected communicator (``comms_t``, cpp/include/raft/comms/comms.hpp:193;
partition merge ``knn_merge_parts``, detail/knn_brute_force_faiss.cuh:55;
the Dask orchestration lives consumer-side in cuML).  This is BASELINE.md
config #5 as a callable library function.

TPU re-design: the reference is multi-controller (one process per GPU,
explicit NCCL verbs); here the whole computation is ONE SPMD program over
a ``jax.sharding.Mesh`` axis:

- the index is row-sharded over ``axis`` (the reference's per-rank
  partitions), queries are replicated — or sharded over an optional
  second ``query_axis``, the 2-D sub-communicator pattern of the
  reference's ``handle.set_subcomm`` (handle.hpp:237);
- each shard runs the local fused distance + top-k;
- local ids are translated to global ids with the shard offset
  (reference id_ranges, knn_brute_force_faiss.cuh:241-255) ON device;
- the cross-shard merge is a selectable **topology**
  (:func:`_merge_topk`):

  * ``"allgather"`` (default): candidates ride ICI via ``all_gather``
    and are re-selected to the global top-k in one wide selection (the
    ``knn_merge_parts`` heap-merge as a single XLA collective);
  * ``"ring"``: ``ppermute`` streams candidate blocks around the axis
    with a running top-k — (nq, 2k) peak merge memory regardless of
    axis size, same total ICI traffic (the distance-matrix instance of
    the ring pattern, SURVEY §5);
  * ``"hierarchical"``: allgather *within* a host group, ring *across*
    groups, with a distance-sorted k-way re-selection at each level —
    HiCCL's hierarchical decomposition (PAPERS.md) applied to top-k
    merging instead of raw collectives.  Group size resolves from
    device placement (:func:`raft_tpu.comms.host_comms.
    axis_host_group_size`: contiguous same-process runs = a host) and
    falls back to the divisor nearest sqrt(axis size) on single-host
    meshes.

Every SPMD program here compiles through
:func:`~raft_tpu.core.profiler.profiled_jit` — never a bare
``jax.jit`` (``ci/style_check.py`` enforces it) — so the serving
layer's warmup proof and loadgen's ``post_warmup_compiles=0`` check
see sharded compiles like every other served primitive, and each
program has a donating executable twin that consumes the (replicated)
query batch, honoring the zero-copy serve contract
(docs/ZERO_COPY.md).

Besides the brute-force search this module owns the *serving-facing*
sharded machinery (docs/SERVING.md "Sharded serving"):

- :func:`shard_knn_index` — commit a row-sharded padded index to the
  mesh once, so every serve batch reuses resident shards instead of
  re-sharding per call;
- :func:`shard_ivf_flat_index` / :func:`mnmg_ivf_flat_search` — the
  slot-sharded IVF-Flat analog: slots (inverted lists) are row-sharded
  over the axis, each shard probes the replicated centroids and scans
  only the probed slots it owns (``slot_ids`` already carry global row
  ids, so no translation step is even needed), and the same merge
  topologies produce the global top-k.

The communicator is resolved from (in order) an explicit ``comms``, the
``handle``'s injected comms (reference ``handle.get_comms()`` idiom),
an explicit ``mesh``/``axis`` pair, or the handle's mesh.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from raft_tpu.core import tuning
from raft_tpu.comms.host_comms import axis_host_group_size, shard_map
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled_jit
from raft_tpu.core.utils import ceildiv
from raft_tpu.mr.buffer import zeros_cached
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.spatial.knn import _IP_FAMILY, _search_one_partition
from raft_tpu.spatial.select_k import select_k

D = DistanceType

# the candidate registry owns the topology set (raft_tpu/core/tuning);
# re-exported here for the callers that enumerate it
MERGE_TOPOLOGIES = tuning.candidates("mnmg_merge")


def _resolve_comms(handle, comms, mesh, axis):
    """(mesh, axis) from the strongest available source."""
    if comms is not None:
        return comms.mesh, comms.axis
    if handle is not None and handle.comms_initialized():
        c = handle.get_comms()
        return c.mesh, c.axis
    if mesh is not None:
        expects(axis is not None and axis in mesh.axis_names,
                "mnmg_knn: axis must name an axis of the given mesh")
        return mesh, axis
    if handle is not None and handle.mesh is not None:
        m = handle.mesh
        if axis is None:
            return m, m.axis_names[0]
        expects(axis in m.axis_names,
                "mnmg_knn: axis %s not in the handle's mesh", axis)
        return m, axis
    from raft_tpu.comms.host_comms import default_mesh

    m = default_mesh()
    if axis is not None:
        expects(axis in m.axis_names,
                "mnmg_knn: axis %s given without a mesh that has it", axis)
    return m, m.axis_names[0]


def resolve_merge(merge: Optional[str], *,
                  devices: Optional[int] = None,
                  n: Optional[int] = None,
                  k: Optional[int] = None) -> str:
    """Resolve the merge-topology knob through the candidate registry:
    explicit argument first, then the ``mnmg_merge`` config ladder
    (override → configure → env ``RAFT_TPU_MNMG_MERGE`` → tuning table
    on the (devices, n, k) shape class → default)."""
    return tuning.resolve("mnmg_merge", merge, site="mnmg",
                          devices=devices, n=n, k=k)


def resolve_group_size(mesh, axis: str,
                       group_size: Optional[int] = None) -> int:
    """Host-group size for the hierarchical merge.

    Explicit ``group_size`` must divide the axis size.  None resolves
    from device placement (:func:`axis_host_group_size` — devices per
    host when hosts are contiguous along the axis) and falls back to
    the divisor of the axis size nearest its square root, the balanced
    two-level decomposition (equal fan-in per level) when placement
    carries no host structure — e.g. the single-process virtual mesh.
    """
    size = int(mesh.shape[axis])
    if group_size is not None:
        g = int(group_size)
        # registry legality (shared LogicError message shape): must
        # divide the merge axis size
        tuning.check("mnmg_group_size", g, site="mnmg", explicit=True,
                     axis_size=size)
        return g
    g = axis_host_group_size(mesh, axis)
    if g is not None and size % g == 0:
        return g
    root = size ** 0.5
    divisors = [d for d in range(1, size + 1) if size % d == 0]
    return min(divisors, key=lambda d: (abs(d - root), d))


# --------------------------------------------------------------------- #
# the cross-shard top-k merge (shared by the brute-force and IVF paths)
# --------------------------------------------------------------------- #
def _ring_steps(best_d, best_i, blk_d, blk_i, k, axis, perm, steps,
                select_min, worst):
    """Stream candidate blocks along ``perm`` for ``steps`` hops with a
    running top-k re-selection (the reference's streaming heap-merge,
    knn_merge_parts, knn_brute_force_faiss.cuh:55, as ppermute + one
    selection per hop)."""
    # tiny shards: pad the running block to the carry width
    best_d, best_i = _pad_to_k(best_d, best_i, k, worst)
    if steps <= 0:
        return best_d, best_i

    def body(_, carry):
        bd, bi, rd, ri = carry
        rd = lax.ppermute(rd, axis, perm)
        ri = lax.ppermute(ri, axis, perm)
        cd = jnp.concatenate([bd, rd], axis=1)
        ci = jnp.concatenate([bi, ri], axis=1)
        nd, ni = select_k(cd, k, select_min=select_min, values=ci)
        return nd, ni, rd, ri

    best_d, best_i, _, _ = lax.fori_loop(
        0, steps, body, (best_d, best_i, blk_d, blk_i))
    return best_d, best_i


def _pad_to_k(d, i, k, worst):
    """Widen a candidate block to k columns with (worst, -1) fillers —
    a shard set whose total candidate width is below k (tiny probed
    lists) must still produce (nq, k) outputs, like the single-device
    running select's inf-initialized carry."""
    if d.shape[1] >= k:
        return d, i
    pad = k - d.shape[1]
    return (jnp.pad(d, ((0, 0), (0, pad)), constant_values=worst),
            jnp.pad(i, ((0, 0), (0, pad)), constant_values=-1))


def _merge_topk(d_loc, gid, k, axis, size, select_min, worst, merge,
                group_size):
    """Merge each shard's masked local candidates ``(d_loc, gid)`` into
    the replicated global top-k, by the selected topology (module doc).
    Runs INSIDE the shard_map body; invalid candidates carry ``worst``
    distance and id -1."""
    if merge == "allgather":
        # one wide collective + one re-selection; the gathered width
        # can undershoot k when every shard's candidate list is narrow
        # (small probed lists) — select what exists, pad the rest
        all_d = lax.all_gather(d_loc, axis, axis=1, tiled=True)
        all_i = lax.all_gather(gid, axis, axis=1, tiled=True)
        kk = min(k, all_d.shape[1])
        out_d, out_i = select_k(all_d, kk, select_min=select_min,
                                values=all_i)
        return _pad_to_k(out_d, out_i, k, worst)
    # both streaming topologies narrow the local block first: every
    # global top-k member on this shard survives its local top-k
    kb = min(k, d_loc.shape[1])
    blk_d, blk_i = select_k(d_loc, kb, select_min=select_min, values=gid)
    if merge == "ring":
        perm = [(i, (i + 1) % size) for i in range(size)]
        return _ring_steps(blk_d, blk_i, blk_d, blk_i, k, axis, perm,
                           size - 1, select_min, worst)
    # hierarchical: allgather within each host group, re-select, then
    # ring the group blocks across groups (HiCCL's decomposition on
    # top-k candidates) — each level ends in a distance-sorted k-way
    # re-selection (select_k over the concatenated candidate lists)
    g = group_size
    n_groups = size // g
    if g > 1:
        groups = [[b * g + i for i in range(g)]
                  for b in range(n_groups)]
        grp_d = lax.all_gather(blk_d, axis, axis=1, tiled=True,
                               axis_index_groups=groups)
        grp_i = lax.all_gather(blk_i, axis, axis=1, tiled=True,
                               axis_index_groups=groups)
        kg = min(k, grp_d.shape[1])
        blk_d, blk_i = select_k(grp_d, kg, select_min=select_min,
                                values=grp_i)
    if n_groups == 1:
        return _ring_steps(blk_d, blk_i, blk_d, blk_i, k, axis, [],
                           0, select_min, worst)
    # ring across groups: every device forwards its group's block to
    # the same in-group rank of the next group, so all g members of a
    # group run the inter-group merge in lockstep (replicated within
    # the group — no leader bottleneck)
    perm = [(i, (i + g) % size) for i in range(size)]
    return _ring_steps(blk_d, blk_i, blk_d, blk_i, k, axis, perm,
                       n_groups - 1, select_min, worst)


# --------------------------------------------------------------------- #
# the brute-force SPMD program (profiled_jit + donating twin)
# --------------------------------------------------------------------- #
def _mnmg_search_impl(index_p, queries, mesh, axis, query_axis, k,
                      k_local, n, rows, metric, metric_arg, tile_n,
                      precision, merge, group_size):
    size = mesh.shape[axis]
    select_min = metric not in _IP_FAMILY
    worst = jnp.inf if select_min else -jnp.inf

    def shard_fn(ix, q):
        # local partition search (reference per-partition stream search)
        d_loc, i_loc = _search_one_partition(ix, q, k_local, metric,
                                             metric_arg, tile_n,
                                             precision)
        # translate to global ids; mask this shard's padding rows
        base = lax.axis_index(axis) * rows
        gid = (i_loc + base).astype(jnp.int32)
        invalid = gid >= n
        d_loc = jnp.where(invalid, worst, d_loc)
        gid = jnp.where(invalid, -1, gid)
        return _merge_topk(d_loc, gid, k, axis, size, select_min,
                           worst, merge, group_size)

    q_spec = (P(query_axis, None) if query_axis is not None
              else P(None, None))
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), q_spec),
        out_specs=(q_spec, q_spec),
        check_rep=False)
    return fn(index_p, queries)


_MNMG_STATICS = ("mesh", "axis", "query_axis", "k", "k_local", "n",
                 "rows", "metric", "metric_arg", "tile_n", "precision",
                 "merge", "group_size")
_mnmg_search_jit = profiled_jit(
    name="mnmg_knn_search",
    static_argnames=_MNMG_STATICS)(_mnmg_search_impl)
# donating twin (docs/ZERO_COPY.md): a separate wrapper, not a flag — a
# donating and a non-donating executable must never share a cache slot.
# The padded serve batch is the intended donor; donation of a
# replicated input is best-effort recycling (XLA keeps a copy when the
# output cannot alias), never a behavior change.
_mnmg_search_jit_donated = profiled_jit(
    name="mnmg_knn_search_donated", static_argnames=_MNMG_STATICS,
    donate_argnames=("queries",))(_mnmg_search_impl)


def shard_knn_index(index, mesh, axis: str):
    """Commit a row-sharded padded index to the mesh ONCE.

    Returns ``(index_p, n)``: the zero-padded ``(rows*size, d)`` array
    committed with ``NamedSharding(mesh, P(axis, None))`` — every
    subsequent :func:`mnmg_knn` / serve batch at this geometry reuses
    the resident shards with no per-call resharding — and the real row
    count ``n`` the program masks against.
    """
    index = jnp.asarray(index)
    expects(index.ndim == 2, "shard_knn_index: (n, d) index required")
    n, d = index.shape
    size = int(mesh.shape[axis])
    rows = ceildiv(n, size)
    n_pad = rows * size
    if n_pad > n:
        index = jnp.concatenate(
            [index, zeros_cached((n_pad - n, d), index.dtype)], axis=0)
    sharding = NamedSharding(mesh, P(axis, None))
    return jax.device_put(index, sharding), n


def mnmg_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    metric: DistanceType = D.L2Expanded,
    metric_arg: float = 2.0,
    handle=None,
    comms=None,
    mesh=None,
    axis: Optional[str] = None,
    query_axis: Optional[str] = None,
    tile_n: int = 8192,
    precision: str = "highest",
    merge: Optional[str] = None,
    group_size: Optional[int] = None,
    donate_queries: bool = False,
    n_rows: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN with the index row-sharded across a mesh axis.

    Parameters
    ----------
    index:
        (n, d) global index rows (sharded over ``axis`` by the
        program), or a pre-committed padded array from
        :func:`shard_knn_index` together with ``n_rows``.
    queries:
        (nq, d) queries, replicated (or sharded over ``query_axis``).
    k:
        Neighbors per query (k <= n).
    metric, metric_arg:
        Distance metric; same dispatch as ``brute_force_knn``.
    handle / comms / mesh+axis:
        Communicator resolution, strongest first (see module doc).
    query_axis:
        Optional second mesh axis to shard queries over; nq must divide
        by its size.
    precision:
        MXU matmul precision for the local searches ("highest" default;
        "default" = single-pass bf16, see ``brute_force_knn``).
    merge:
        Cross-shard merge topology: ``"allgather"`` | ``"ring"`` |
        ``"hierarchical"`` (module doc).  None resolves the
        ``mnmg_merge`` config knob.  Identical results up to
        distance-tie order.
    group_size:
        Hierarchical host-group size (must divide the axis size); None
        auto-resolves (:func:`resolve_group_size`).
    donate_queries:
        Consume the queries buffer — routes into the donating
        executable twin (docs/ZERO_COPY.md; the serve layer's padded
        batch is the intended donor).
    n_rows:
        Real row count when ``index`` is already the padded sharded
        array from :func:`shard_knn_index` (skips the per-call pad).

    Returns
    -------
    (distances, indices): (nq, k) global results, best-first, int32
    global ids; replicated along ``axis`` (and sharded along
    ``query_axis`` when given).
    """
    mesh_, axis_ = _resolve_comms(handle, comms, mesh, axis)
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "mnmg_knn: index/query dimensionality mismatch")
    size = int(mesh_.shape[axis_])
    if n_rows is not None:
        n = int(n_rows)
        expects(index.shape[0] % size == 0,
                "mnmg_knn: pre-sharded index rows %d not divisible by "
                "axis size %d", index.shape[0], size)
        index_p = index
    else:
        n = index.shape[0]
        index_p, _ = shard_knn_index(index, mesh_, axis_)
    nq = queries.shape[0]
    expects(0 < k <= n, "mnmg_knn: k=%d out of range for n=%d", k, n)
    if query_axis is not None:
        expects(query_axis in mesh_.axis_names,
                "mnmg_knn: query_axis %s not in mesh", query_axis)
        expects(nq % mesh_.shape[query_axis] == 0,
                "mnmg_knn: nq=%d not divisible by query_axis size %d",
                nq, mesh_.shape[query_axis])

    rows = index_p.shape[0] // size
    n_pad = rows * size
    # widen the local k by the pad count: a zero pad row can *beat* real
    # rows under any metric (its L2 distance is just ||q||^2), so pads may
    # occupy local top-k slots — the widening guarantees >= k real
    # candidates survive the post-search mask
    k_local = min(k + (n_pad - n), rows)
    merge = resolve_merge(merge, devices=size, n=n, k=k)
    group_size = (resolve_group_size(mesh_, axis_, group_size)
                  if merge == "hierarchical" else 1)

    fn = _mnmg_search_jit_donated if donate_queries else _mnmg_search_jit
    dist, idx = fn(index_p, queries, mesh=mesh_, axis=axis_,
                   query_axis=query_axis, k=k, k_local=k_local, n=n,
                   rows=rows, metric=metric, metric_arg=metric_arg,
                   tile_n=tile_n, precision=precision, merge=merge,
                   group_size=group_size)

    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(jnp.maximum(dist, 0.0))
    return dist, idx


# --------------------------------------------------------------------- #
# slot-sharded IVF-Flat (the ANN serving shard, docs/SERVING.md)
# --------------------------------------------------------------------- #
class ShardedIVFFlat(NamedTuple):
    """An IVF-Flat index with its slot stores row-sharded over a mesh
    axis — the serving shard :class:`~raft_tpu.serve.ANNService` owns
    when constructed with ``axis=``.

    Centroids are replicated (every shard probes the same coarse
    quantizer — identical probe selection on every device, no
    collective needed); ``slot_vecs`` / ``slot_norms`` / ``slot_ids``
    are sharded over the (padded) slot dimension, and
    ``cent_slots_local`` maps each centroid's global slot list into
    per-shard LOCAL slot ids (-1 = not owned by that shard), so a
    shard scans exactly the probed slots it holds.  ``slot_ids``
    already carry global row ids — the id-translation step of the
    brute-force path falls away entirely.
    """

    mesh: object
    axis: str
    centroids: jnp.ndarray         # (nlist, d) replicated
    slot_vecs: jnp.ndarray         # (slots_pad, cap, d) sharded
    slot_norms: jnp.ndarray        # (slots_pad, cap) sharded
    slot_ids: jnp.ndarray          # (slots_pad, cap) sharded, -1 pad
    cent_slots_local: jnp.ndarray  # (size, nlist, max_slots) sharded
    metric: DistanceType
    nprobe: int

    @property
    def nlist(self) -> int:
        return int(self.centroids.shape[0])


def shard_ivf_flat_index(index, mesh, axis: str) -> ShardedIVFFlat:
    """Slot-shard an :class:`~raft_tpu.spatial.ann.IVFFlatIndex` over
    ``axis`` and commit the shards to the mesh (class doc above)."""
    from raft_tpu.spatial.ann import IVFFlatIndex

    expects(isinstance(index, IVFFlatIndex),
            "shard_ivf_flat_index: IVFFlatIndex required, got %r",
            type(index).__name__)
    size = int(mesh.shape[axis])
    n_slots, cap, d = index.slot_vecs.shape
    rows = ceildiv(n_slots, size)
    pad = rows * size - n_slots
    slot_vecs = index.slot_vecs
    norms = index.slot_norms
    if norms is None:   # hand-built legacy tuple
        norms = jnp.sum(slot_vecs * slot_vecs, -1)
    slot_ids = index.slot_ids
    if pad:
        slot_vecs = jnp.concatenate(
            [slot_vecs, zeros_cached((pad, cap, d), slot_vecs.dtype)],
            axis=0)
        norms = jnp.concatenate(
            [norms, zeros_cached((pad, cap), norms.dtype)], axis=0)
        slot_ids = jnp.concatenate(
            [slot_ids, jnp.full((pad, cap), -1, slot_ids.dtype)],
            axis=0)
    # per-shard local slot map: shard r owns global slots
    # [r*rows, (r+1)*rows); everything else reads -1 ("not mine"), the
    # same not-a-slot sentinel the probe scan already compacts away
    cs = np.asarray(index.cent_slots)                # (nlist, max_slots)
    bases = (np.arange(size) * rows)[:, None, None]
    owned = (cs[None] >= bases) & (cs[None] < bases + rows)
    local = np.where(owned, cs[None] - bases, -1).astype(np.int32)
    shard = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    return ShardedIVFFlat(
        mesh=mesh, axis=axis,
        centroids=jax.device_put(index.centroids, rep),
        slot_vecs=jax.device_put(slot_vecs, NamedSharding(
            mesh, P(axis, None, None))),
        slot_norms=jax.device_put(norms, NamedSharding(
            mesh, P(axis, None))),
        slot_ids=jax.device_put(slot_ids, NamedSharding(
            mesh, P(axis, None))),
        cent_slots_local=jax.device_put(jnp.asarray(local), NamedSharding(
            mesh, P(axis, None, None))),
        metric=DistanceType(int(index.metric)),
        nprobe=int(index.nprobe))


def _mnmg_ivf_search_impl(centroids, slot_vecs, slot_norms, slot_ids,
                          cent_slots_local, q, mesh, axis, k, nprobe,
                          metric, select_impl, merge, group_size):
    from raft_tpu.distance.pairwise import expanded_sq_dists

    size = mesh.shape[axis]

    def shard_fn(cent, sv, sn, si, cs, qq):
        cs = cs[0]                       # (nlist, max_slots) local map
        nq = qq.shape[0]
        qn = jnp.sum(qq * qq, axis=1)
        # identical probe selection on every shard (replicated
        # centroids — no collective needed)
        qc = expanded_sq_dists(qq, cent)
        _, probes = select_k(qc, min(nprobe, cent.shape[0]),
                             select_min=True, impl=select_impl)
        slots = cs[probes].reshape(nq, -1)     # local slot ids, -1 pad
        # valid-first compaction (one stable sort, the _probe_scan_
        # search idiom), then a STATIC truncation: a shard cannot own
        # more live probed slots than it holds slots at all
        _, slots = lax.sort(((slots < 0).astype(jnp.int32), slots),
                            dimension=1, num_keys=1, is_stable=True)
        slots = slots[:, :min(slots.shape[1], sv.shape[0])]
        # ONE-SHOT scan of every probed owned slot — deliberately not
        # the single-device running-select fori_loop: a while loop
        # whose shape/trip structure is fed by per-shard data
        # mis-executes inside a manually partitioned (shard_map) jitted
        # program on the CPU backend (observed: per-row slot/query
        # misalignment; only straight-line bodies are safe), and
        # uniform straight-line control flow across shards is the
        # conservative SPMD stance anyway.  The gathered (nq, S, cap,
        # d) block feeds ONLY the einsum, which fuses the gather away
        # (the slot_norms finding, spatial/ann.py) — peak memory is the
        # (nq, S, cap) distance block, bounded by the static probe
        # budget S <= min(nprobe * max_slots, local slots).
        valid = slots >= 0
        slx = jnp.where(valid, slots, 0)
        vecs = sv[slx]                               # (nq, S, cap, d)
        dist = (qn[:, None, None] + sn[slx]
                - 2.0 * jnp.einsum("nd,nscd->nsc", qq, vecs,
                                   precision="highest"))
        ids = jnp.where(valid[:, :, None], si[slx], -1)
        ids = ids.reshape(nq, -1).astype(jnp.int32)
        dist = jnp.where(ids >= 0,
                         jnp.maximum(dist.reshape(nq, -1), 0.0),
                         jnp.inf).astype(
                             jnp.result_type(qq.dtype, jnp.float32))
        kk = min(k, dist.shape[1])
        d_loc, i_loc = select_k(dist, kk, select_min=True, values=ids,
                                impl=select_impl)
        d_merged, i_merged = _merge_topk(d_loc, i_loc, k, axis, size,
                                         True, jnp.inf, merge,
                                         group_size)
        if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
            d_merged = jnp.sqrt(d_merged)
        return d_merged, i_merged

    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, None), P(axis, None, None), P(axis, None),
                  P(axis, None), P(axis, None, None), P(None, None)),
        out_specs=(P(None, None), P(None, None)),
        check_rep=False)
    return fn(centroids, slot_vecs, slot_norms, slot_ids,
              cent_slots_local, q)


_MNMG_IVF_STATICS = ("mesh", "axis", "k", "nprobe", "metric",
                     "select_impl", "merge", "group_size")
_mnmg_ivf_search_jit = profiled_jit(
    name="mnmg_ivf_flat_search",
    static_argnames=_MNMG_IVF_STATICS)(_mnmg_ivf_search_impl)
_mnmg_ivf_search_jit_donated = profiled_jit(
    name="mnmg_ivf_flat_search_donated",
    static_argnames=_MNMG_IVF_STATICS,
    donate_argnames=("q",))(_mnmg_ivf_search_impl)


def mnmg_ivf_flat_search(sharded: ShardedIVFFlat, queries, k: int,
                         nprobe: Optional[int] = None, *,
                         select_impl: Optional[str] = None,
                         merge: Optional[str] = None,
                         group_size: Optional[int] = None,
                         donate_queries: bool = False,
                         delta=None
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search a slot-sharded IVF-Flat index (one SPMD program: probe →
    per-shard slot scan → cross-shard top-k merge by the selected
    topology).  Results match the single-device
    :func:`~raft_tpu.spatial.ann.ivf_flat_search` at the same
    ``nprobe`` up to distance-tie order.

    ``delta=(vectors, ids)`` merges the append-only (replicated) delta
    segment into the result stream after the sharded program, through
    the same :func:`~raft_tpu.spatial.ann._delta_merge_impl` programs
    the single-device path uses; with ``donate_queries`` the query
    buffer is donated to the LAST consuming program (the delta merge
    when present, the sharded search otherwise — the
    ``tiled_knn_donated`` contract, docs/ZERO_COPY.md).
    """
    from raft_tpu.spatial.ann import _merge_delta, _validate_nprobe

    q = jnp.asarray(queries)
    expects(q.ndim == 2 and q.shape[1] == sharded.centroids.shape[1],
            "mnmg_ivf_flat_search: (nq, %d) queries required, got %r",
            sharded.centroids.shape[1], tuple(q.shape))
    nprobe = sharded.nprobe if nprobe is None else nprobe
    nprobe = _validate_nprobe("mnmg_ivf_flat_search", nprobe,
                              sharded.nlist)
    merge = resolve_merge(merge,
                          devices=int(sharded.mesh.shape[sharded.axis]),
                          k=k)
    group_size = (resolve_group_size(sharded.mesh, sharded.axis,
                                     group_size)
                  if merge == "hierarchical" else 1)
    donate_base = donate_queries and delta is None
    fn = (_mnmg_ivf_search_jit_donated if donate_base
          else _mnmg_ivf_search_jit)
    out = fn(sharded.centroids, sharded.slot_vecs, sharded.slot_norms,
             sharded.slot_ids, sharded.cent_slots_local, q,
             mesh=sharded.mesh, axis=sharded.axis, k=k, nprobe=nprobe,
             metric=sharded.metric, select_impl=select_impl,
             merge=merge, group_size=group_size)
    if delta is not None:
        out = _merge_delta(out, delta, q, k, sharded.metric,
                           donate_queries)
    return out
