"""Multi-node multi-device brute-force kNN over a mesh axis.

Reference: the MNMG mode of ``brute_force_knn`` — each rank searches its
row partition of the index locally, then results are merged through the
injected communicator (``comms_t``, cpp/include/raft/comms/comms.hpp:193;
partition merge ``knn_merge_parts``, detail/knn_brute_force_faiss.cuh:55;
the Dask orchestration lives consumer-side in cuML).  This is BASELINE.md
config #5 as a callable library function.

TPU re-design: the reference is multi-controller (one process per GPU,
explicit NCCL verbs); here the whole computation is ONE SPMD program over
a ``jax.sharding.Mesh`` axis:

- the index is row-sharded over ``axis`` (the reference's per-rank
  partitions), queries are replicated — or sharded over an optional
  second ``query_axis``, the 2-D sub-communicator pattern of the
  reference's ``handle.set_subcomm`` (handle.hpp:237);
- each shard runs the local fused distance + top-k;
- local ids are translated to global ids with the shard offset
  (reference id_ranges, knn_brute_force_faiss.cuh:241-255);
- candidates ride ICI via ``all_gather`` along the axis and are
  re-selected to the global top-k (the ``knn_merge_parts`` heap-merge
  becomes one wide re-selection) — so the merge compiles to a single
  XLA collective instead of eager NCCL calls;
- ``merge="ring"`` instead streams candidate blocks around the axis
  with ``ppermute`` and keeps a running top-k: peak merge memory is
  (nq, 2k) regardless of axis size (vs (nq, size*k) for the allgather),
  the same total ICI traffic — the distance-matrix instance of the ring
  pattern (SURVEY §5), and the closest TPU shape to the reference's
  streaming heap-merge (knn_merge_parts, knn_brute_force_faiss.cuh:55).

The communicator is resolved from (in order) an explicit ``comms``, the
``handle``'s injected comms (reference ``handle.get_comms()`` idiom),
an explicit ``mesh``/``axis`` pair, or the handle's mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.host_comms import shard_map
from raft_tpu.core.error import expects
from raft_tpu.core.utils import ceildiv
from raft_tpu.mr.buffer import zeros_cached
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.spatial.knn import _IP_FAMILY, _search_one_partition
from raft_tpu.spatial.select_k import select_k

D = DistanceType


def _resolve_comms(handle, comms, mesh, axis):
    """(mesh, axis) from the strongest available source."""
    if comms is not None:
        return comms.mesh, comms.axis
    if handle is not None and handle.comms_initialized():
        c = handle.get_comms()
        return c.mesh, c.axis
    if mesh is not None:
        expects(axis is not None and axis in mesh.axis_names,
                "mnmg_knn: axis must name an axis of the given mesh")
        return mesh, axis
    if handle is not None and handle.mesh is not None:
        m = handle.mesh
        if axis is None:
            return m, m.axis_names[0]
        expects(axis in m.axis_names,
                "mnmg_knn: axis %s not in the handle's mesh", axis)
        return m, axis
    from raft_tpu.comms.host_comms import default_mesh

    m = default_mesh()
    if axis is not None:
        expects(axis in m.axis_names,
                "mnmg_knn: axis %s given without a mesh that has it", axis)
    return m, m.axis_names[0]


def mnmg_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    metric: DistanceType = D.L2Expanded,
    metric_arg: float = 2.0,
    handle=None,
    comms=None,
    mesh=None,
    axis: Optional[str] = None,
    query_axis: Optional[str] = None,
    tile_n: int = 8192,
    precision: str = "highest",
    merge: str = "allgather",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN with the index row-sharded across a mesh axis.

    Parameters
    ----------
    index:
        (n, d) global index rows (sharded over ``axis`` by the program).
    queries:
        (nq, d) queries, replicated (or sharded over ``query_axis``).
    k:
        Neighbors per query (k <= n).
    metric, metric_arg:
        Distance metric; same dispatch as ``brute_force_knn``.
    handle / comms / mesh+axis:
        Communicator resolution, strongest first (see module doc).
    query_axis:
        Optional second mesh axis to shard queries over; nq must divide
        by its size.
    precision:
        MXU matmul precision for the local searches ("highest" default;
        "default" = single-pass bf16, see ``brute_force_knn``).
    merge:
        "allgather" (default): one wide collective + one re-selection.
        "ring": ppermute candidate blocks around the axis with a running
        top-k — (nq, 2k) peak merge memory regardless of axis size
        (module doc).  Identical results up to distance-tie order.

    Returns
    -------
    (distances, indices): (nq, k) global results, best-first, int32
    global ids; replicated along ``axis`` (and sharded along
    ``query_axis`` when given).
    """
    mesh_, axis_ = _resolve_comms(handle, comms, mesh, axis)
    expects(index.ndim == 2 and queries.ndim == 2
            and index.shape[1] == queries.shape[1],
            "mnmg_knn: index/query dimensionality mismatch")
    n, d = index.shape
    nq = queries.shape[0]
    expects(0 < k <= n, "mnmg_knn: k=%d out of range for n=%d", k, n)
    size = mesh_.shape[axis_]
    if query_axis is not None:
        expects(query_axis in mesh_.axis_names,
                "mnmg_knn: query_axis %s not in mesh", query_axis)
        expects(nq % mesh_.shape[query_axis] == 0,
                "mnmg_knn: nq=%d not divisible by query_axis size %d",
                nq, mesh_.shape[query_axis])

    rows = ceildiv(n, size)
    n_pad = rows * size
    if n_pad > n:
        # pad tail from the shared zeros cache (docs/ZERO_COPY.md):
        # repeated mnmg searches at a geometry re-pad the same (pad, d)
        # tail every call, and jnp.pad would materialize a fresh device
        # zeros block each time — the cached block makes the eager pad
        # a concatenate against an existing device buffer
        index_p = jnp.concatenate(
            [index, zeros_cached((n_pad - n, d), index.dtype)], axis=0)
    else:
        index_p = index
    select_min = metric not in _IP_FAMILY
    worst = jnp.inf if select_min else -jnp.inf
    # widen the local k by the pad count: a zero pad row can *beat* real
    # rows under any metric (its L2 distance is just ||q||^2), so pads may
    # occupy local top-k slots — the widening guarantees >= k real
    # candidates survive the post-search mask
    k_local = min(k + (n_pad - n), rows)

    expects(merge in ("allgather", "ring"),
            "mnmg_knn: unknown merge %s", merge)

    def shard_fn(ix, q):
        # local partition search (reference per-partition stream search)
        d_loc, i_loc = _search_one_partition(ix, q, k_local, metric,
                                             metric_arg, tile_n, precision)
        # translate to global ids; mask this shard's padding rows
        base = lax.axis_index(axis_) * rows
        gid = (i_loc + base).astype(jnp.int32)
        invalid = gid >= n
        d_loc = jnp.where(invalid, worst, d_loc)
        gid = jnp.where(invalid, -1, gid)
        if merge == "ring":
            # narrow the masked local candidates to k (every global
            # top-k member on this shard survives its local top-k), then
            # stream blocks around the ring with a running re-selection
            blk_d, blk_i = select_k(d_loc, min(k, k_local),
                                    select_min=select_min, values=gid)
            best_d, best_i = blk_d, blk_i
            perm = [(i, (i + 1) % size) for i in range(size)]

            def body(_, carry):
                bd, bi, rd, ri = carry
                rd = lax.ppermute(rd, axis_, perm)
                ri = lax.ppermute(ri, axis_, perm)
                cd = jnp.concatenate([bd, rd], axis=1)
                ci = jnp.concatenate([bi, ri], axis=1)
                nd, ni = select_k(cd, k, select_min=select_min, values=ci)
                return nd, ni, rd, ri

            if blk_d.shape[1] < k:  # tiny shards: pad the running block
                pad = k - blk_d.shape[1]
                best_d = jnp.pad(blk_d, ((0, 0), (0, pad)),
                                 constant_values=worst)
                best_i = jnp.pad(blk_i, ((0, 0), (0, pad)),
                                 constant_values=-1)
            best_d, best_i, _, _ = lax.fori_loop(
                0, size - 1, body, (best_d, best_i, blk_d, blk_i))
            return best_d, best_i
        # merge across the axis: allgather candidates, one re-selection
        all_d = lax.all_gather(d_loc, axis_, axis=1, tiled=True)
        all_i = lax.all_gather(gid, axis_, axis=1, tiled=True)
        return select_k(all_d, k, select_min=select_min, values=all_i)

    q_spec = P(query_axis, None) if query_axis is not None else P(None, None)
    fn = shard_map(
        shard_fn, mesh=mesh_,
        in_specs=(P(axis_, None), q_spec),
        out_specs=(q_spec, q_spec),
        check_rep=False)
    dist, idx = jax.jit(fn)(index_p, queries)

    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(jnp.maximum(dist, 0.0))
    return dist, idx
