"""Random ball cover: exact kNN for low-dim data via landmark pruning.

Reference: spatial/knn/ball_cover.hpp:32,77,142 (``rbc_build_index``,
``rbc_all_knn_query``, ``rbc_knn_query``) and detail/ball_cover.cuh — index
= √m sampled landmarks, every point 1-NN-assigned to a landmark, members
sorted by landmark with per-landmark radius (:64-318); query = k closest
landmarks first, then triangle-inequality-pruned passes over remaining
landmarks (:218-260).

TPU design: the per-thread heap + early-exit register kernels
(detail/ball_cover/registers.cuh) become **ranked dense group scans**: each
query orders landmarks by distance once; a ``lax.while_loop`` scans one
ranked group per step (a padded (nq, group_max, d) gather + batched
distance + running top-k merge) and stops as soon as the triangle
inequality ``d(q, landmark) − radius > kth_bound`` prunes every remaining
landmark for every query — the same exactness argument as the reference,
with dynamic trip count instead of per-thread early exit.

Supported metrics: L2 family and Haversine (reference restricts to the
same, ball_cover.hpp docs).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import expanded_sq_dists
from raft_tpu.spatial.haversine import haversine_distances
from raft_tpu.spatial.knn import knn_merge_parts
from raft_tpu.spatial.select_k import select_k

D = DistanceType
_SUPPORTED = (D.L2Expanded, D.L2SqrtExpanded, D.L2Unexpanded,
              D.L2SqrtUnexpanded, D.Haversine)


class BallCoverIndex(NamedTuple):
    """(reference BallCoverIndex, ball_cover_common.h:38)"""

    X: jnp.ndarray            # (m, d) original data
    landmarks: jnp.ndarray    # (L, d) sampled landmark coordinates
    groups: jnp.ndarray       # (L, gmax) member row ids, -1 pad
    radius: jnp.ndarray       # (L,) max member distance per landmark
    metric: DistanceType


def _dists(x, y, metric):
    """(m, n) distances in the metric's *pruning* space (root form so the
    triangle inequality holds; L2 results are squared on report if the
    caller's metric is the squared form)."""
    if metric == D.Haversine:
        return haversine_distances(x, y)
    return jnp.sqrt(expanded_sq_dists(x, y))


def rbc_build_index(X, metric: DistanceType = D.L2SqrtExpanded,
                    n_landmarks: int | None = None,
                    seed: int = 0) -> BallCoverIndex:
    """Build the ball cover (reference rbc_build_index, ball_cover.hpp:32;
    n_landmarks defaults to √m, ball_cover_common.h:55)."""
    X = jnp.asarray(X)
    m, dim = X.shape
    expects(metric in _SUPPORTED,
            "rbc_build_index: unsupported metric %d", int(metric))
    if metric == D.Haversine:
        expects(dim == 2, "haversine ball cover requires 2-d lat/lon")
    L = n_landmarks or max(int(np.sqrt(m)), 1)

    rng = np.random.default_rng(seed)
    lm_ids = rng.choice(m, size=L, replace=False)
    landmarks = X[jnp.asarray(lm_ids)]

    # 1-NN assign every point to a landmark (m × L dense — L = √m)
    dl = _dists(X, landmarks, metric)
    owner = np.asarray(jnp.argmin(dl, axis=1))
    dist_own = np.asarray(jnp.min(dl, axis=1))

    counts = np.bincount(owner, minlength=L)
    gmax = max(int(counts.max()), 1)

    from raft_tpu.core import native
    nat = native.pack_groups(owner, dist_own, L, gmax)
    if nat is not None:
        groups64, radius64 = nat
        groups = groups64.astype(np.int32)
        radius = radius64.astype(np.float32)
    else:
        groups = np.full((L, gmax), -1, np.int32)
        fill = np.zeros(L, np.int64)
        order = np.argsort(dist_own)[::-1]  # reference sorts members by dist
        for i in order:
            l = owner[i]
            groups[l, fill[l]] = i
            fill[l] += 1
        radius = np.zeros(L, np.float32)
        np.maximum.at(radius, owner, dist_own)
    return BallCoverIndex(X, landmarks, jnp.asarray(groups),
                          jnp.asarray(radius), metric)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _rbc_query_jit(X, landmarks, groups, radius, q, k, metric):
    nq = q.shape[0]
    L, gmax = groups.shape
    m = X.shape[0]

    ql = _dists(q, landmarks, metric)                 # (nq, L)
    rank_d, rank_l = select_k(ql, L, select_min=True)  # full ordering
    # suffix min over ranked landmarks of (d - radius): if this exceeds the
    # current kth bound, no remaining landmark can improve the result
    slack = rank_d - radius[rank_l]
    suffix_min = jax.lax.associative_scan(jnp.minimum, slack, reverse=True,
                                          axis=1)

    worst = jnp.inf
    best_d0 = jnp.full((nq, k), worst, jnp.float32)
    best_i0 = jnp.full((nq, k), -1, jnp.int32)

    def cond(state):
        r, best_d, best_i, _ = state
        bound = best_d[:, -1]
        # landmark ranked < r already scanned; prune the rest?
        more = r < L
        alive = jnp.any(suffix_min[:, jnp.minimum(r, L - 1)] <= bound)
        return more & alive

    def body(state):
        r, best_d, best_i, steps = state
        lm = rank_l[:, jnp.minimum(r, L - 1)]          # (nq,) landmark ids
        gids = groups[lm]                              # (nq, gmax)
        vecs = X[jnp.where(gids >= 0, gids, 0)]        # (nq, gmax, d)
        if metric == D.Haversine:
            sin_lat = jnp.sin(0.5 * (q[:, None, 0] - vecs[..., 0]))
            sin_lon = jnp.sin(0.5 * (q[:, None, 1] - vecs[..., 1]))
            rdist = sin_lat**2 + (jnp.cos(q[:, None, 0]) *
                                  jnp.cos(vecs[..., 0]) * sin_lon**2)
            dd = 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(rdist, 0.0, 1.0)))
        else:
            dd = (jnp.sum(q * q, 1)[:, None] + jnp.sum(vecs * vecs, -1)
                  - 2.0 * jnp.einsum("nd,ngd->ng", q, vecs,
                                     precision="highest"))
            dd = jnp.sqrt(jnp.maximum(dd, 0.0))
        dd = jnp.where(gids >= 0, dd, worst)
        # gids carried as the selection payload (variadic sort path) —
        # a select-then-take_along_axis gather is a serial scalar loop
        # on TPU (r4 tile-merge finding)
        bd, bi = select_k(dd, min(k, gmax), select_min=True, values=gids)
        if bd.shape[1] < k:
            pad = k - bd.shape[1]
            bd = jnp.pad(bd, ((0, 0), (0, pad)), constant_values=worst)
            bi = jnp.pad(bi, ((0, 0), (0, pad)), constant_values=-1)
        cand_d = jnp.stack([best_d, bd])
        cand_i = jnp.stack([best_i, bi])
        best_d, best_i = knn_merge_parts(cand_d, cand_i, k)
        return r + 1, best_d, best_i, steps + 1

    _, best_d, best_i, steps = jax.lax.while_loop(
        cond, body, (jnp.int32(0), best_d0, best_i0, jnp.int32(0)))

    if metric in (D.L2Expanded, D.L2Unexpanded):
        best_d = best_d * best_d
    return best_d, best_i, steps


def rbc_knn_query(index: BallCoverIndex, k: int, queries
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact kNN against the indexed set (reference rbc_knn_query,
    ball_cover.hpp:142)."""
    q = jnp.asarray(queries)
    d, i, _ = _rbc_query_jit(index.X, index.landmarks, index.groups,
                             index.radius, q, k,
                             DistanceType(int(index.metric)))
    return d, i


def rbc_all_knn_query(index: BallCoverIndex, k: int
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All-points kNN (X vs X, self included — reference
    rbc_all_knn_query, ball_cover.hpp:77)."""
    return rbc_knn_query(index, k, index.X)
