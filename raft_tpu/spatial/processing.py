"""Metric pre/post-processors for kNN.

Reference: cpp/include/raft/spatial/knn/detail/processing.hpp:38-187.
Expanded metrics are reduced to inner products by transforming the data:
cosine L2-normalizes rows (CosineMetricProcessor::preprocess) and
correlation mean-centers first (CorrelationMetricProcessor::preprocess);
after the inner-product search, ``postprocess`` maps similarities to
distances via ``1 - sim`` (processing.hpp:109).

The reference mutates device buffers in place and ``revert``s afterwards;
the TPU design is functional — ``preprocess`` returns a transformed copy
and ``revert`` is the identity on the caller's original array (kept for
API parity, documented as a no-op).
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.distance.distance_type import DistanceType


class MetricProcessor:
    """Identity processor (reference DefaultMetricProcessor,
    processing.hpp:166)."""

    def preprocess(self, data: jnp.ndarray) -> jnp.ndarray:
        return data

    def revert(self, data: jnp.ndarray) -> jnp.ndarray:
        return data

    def postprocess(self, distances: jnp.ndarray) -> jnp.ndarray:
        return distances


class CosineMetricProcessor(MetricProcessor):
    """Row-normalize so inner product = cosine similarity; distances are
    ``1 - sim`` (processing.hpp:50-113)."""

    def preprocess(self, data: jnp.ndarray) -> jnp.ndarray:
        norms = jnp.sqrt(jnp.sum(data * data, axis=1, keepdims=True))
        return data / jnp.where(norms == 0, 1.0, norms)

    def postprocess(self, distances: jnp.ndarray) -> jnp.ndarray:
        return 1.0 - distances


class CorrelationMetricProcessor(CosineMetricProcessor):
    """Mean-center then normalize so inner product = Pearson r
    (processing.hpp:117-163)."""

    def preprocess(self, data: jnp.ndarray) -> jnp.ndarray:
        centered = data - jnp.mean(data, axis=1, keepdims=True)
        return super().preprocess(centered)


def create_processor(metric: DistanceType) -> MetricProcessor:
    """Factory matching reference create_processor (processing.hpp:173)."""
    if metric == DistanceType.CosineExpanded:
        return CosineMetricProcessor()
    if metric == DistanceType.CorrelationExpanded:
        return CorrelationMetricProcessor()
    return MetricProcessor()
