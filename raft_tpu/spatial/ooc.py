"""Out-of-core IVF-Flat search: host-resident slot store, streamed scan.

Every search path in :mod:`raft_tpu.spatial.ann` assumes the whole
slot store is device-resident; this module is the arm for indexes
**bigger than device memory** (ROADMAP item 3, the libhclooc overlapped
tile pipeline from PAPERS.md).  The split:

- **device-resident metadata** (small, O(n_slots·cap) ints/floats):
  centroids, ``cent_slots``, ``slot_ids``, ``slot_norms`` — everything
  the probe and the candidate bookkeeping need;
- **host-resident vectors**: the ``(n_slots, cap, d)`` slot store —
  the ~all of the index's bytes — stays numpy;
- **a device working set**: a fixed *hot set* of frequency-promoted
  slots (owned by the caller, typically
  :class:`raft_tpu.serve.ANNService`) plus a
  :class:`~raft_tpu.mr.tile_pool.TilePool` staging budget the cold
  slots stream through.

Search (:func:`ooc_ivf_flat_search`) per batch:

1. probe on device (same ``expanded_sq_dists`` + ``select_k`` as the
   resident path), fetch the per-query probed-slot lists to host (a
   few KB — the one D2H sync);
2. split the distinct probed slots into hot hits and cold misses
   (``raft_tpu_tile_{hits,misses}_total``);
3. scan the hot subset with the resident path's gather+einsum step
   over the hot-set block;
4. stream the cold slots through the pool in fixed-shape tiles,
   **double-buffered**: the transfer of tile N+1 is issued right after
   the scan of tile N is dispatched, so the H2D copy overlaps the scan
   (``overlap=False`` is the measured synchronous baseline); each
   staged tile is DONATED to its scan program (pool-owned fresh
   storage — docs/ZERO_COPY.md);
5. merge through the same running ``select_k`` seam as the resident
   scan; the delta segment merges after
   (:func:`raft_tpu.spatial.ann._delta_merge_impl`), unchanged.

Identity contract: every probed ``(query, candidate)`` pair's distance
is computed by the *same arithmetic* as the resident path (precomputed
slot norms + one ``"nd,ncd->nc"`` highest-precision einsum over the
gathered slot block), each pair is scanned exactly once, and candidate
membership is exact — so results match the resident search bit-for-bit
except on exact distance ties at the k-th boundary, where the scan
order (hot first, then tiles) may keep a different survivor (the same
caveat the sharded path documents).  Recall@k is identical.

Executable cardinality stays bounded (the zero-post-warmup-compiles
proof): the probe program is shaped by (rung, nprobe cell), the scan
program by (rung, part size) with exactly two part sizes — the hot set
H and the tile ``tile_slots`` — however many tiles stream through.

The ``jax.device_put`` ban (``ci/style_check.py``, ``ooc-resident-ok``
marker) applies to this file: the point of the tier is that the full
store never lands on device, so the only transfer sites are the pool's
per-tile put and the budget-bounded hot-set materialization below.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.handle import record_on_handle
from raft_tpu.core.profiler import default_profiler, profiled_jit
from raft_tpu.distance.distance_type import DistanceType
from raft_tpu.distance.pairwise import expanded_sq_dists
from raft_tpu.mr.tile_pool import TilePool, _pool_counter
from raft_tpu.spatial.ann import (IVFFlatIndex, _assign_labels,
                                  _extend_slot_layout, _merge_delta,
                                  _validate_nprobe)
from raft_tpu.spatial.select_k import select_k

D = DistanceType

__all__ = ["OocIVFFlat", "ivf_flat_to_ooc", "ooc_ivf_flat_search",
           "ooc_extend", "ooc_reconstruct", "materialize_hot"]


class OocIVFFlat(NamedTuple):
    """IVF-Flat index with the slot store held on HOST (module doc).

    Immutable like :class:`~raft_tpu.spatial.ann.IVFFlatIndex` — an
    atomic snapshot swap (compaction) builds a new one; in-flight
    searches keep gathering from the old ``store``."""

    centroids: jnp.ndarray      # (nlist, d) device
    slot_ids: jnp.ndarray       # (n_slots, cap) int32 device, -1 pad
    slot_norms: jnp.ndarray     # (n_slots, cap) f32 device
    cent_slots: jnp.ndarray     # (nlist, max_slots) int32 device
    slot_centroid: np.ndarray   # (n_slots,) int32 HOST (extend/remap)
    list_sizes: jnp.ndarray     # (nlist,)
    metric: DistanceType
    nprobe: int
    store: np.ndarray           # (n_slots, cap, d) HOST — the bulk

    @property
    def n_slots(self) -> int:
        return int(self.store.shape[0])

    @property
    def cap(self) -> int:
        return int(self.store.shape[1])

    def slot_bytes(self) -> int:
        """Device bytes one resident slot of vectors costs."""
        return (self.cap * int(self.store.shape[2])
                * self.store.dtype.itemsize)

    def store_bytes(self) -> int:
        """Total vector bytes of the host store — what the device
        budget is measured against."""
        return int(self.store.nbytes)


def ivf_flat_to_ooc(index: IVFFlatIndex) -> OocIVFFlat:
    """Demote a resident :class:`IVFFlatIndex` to the out-of-core form:
    the slot vectors move to a host numpy store (dropping the caller's
    reference to ``index`` then frees the device copy); the metadata
    stays device-resident.  Builds at billion scale would assemble the
    host store directly (:func:`ooc_extend` shows the shape) — this
    converter is the bridge from the existing build path."""
    expects(isinstance(index, IVFFlatIndex),
            "ivf_flat_to_ooc: expected IVFFlatIndex, got %r",
            type(index).__name__)
    store = np.asarray(index.slot_vecs)
    norms = (index.slot_norms if index.slot_norms is not None
             else jnp.asarray(np.einsum("scd,scd->sc", store, store)))
    slot_centroid = np.asarray(index.slot_centroid, np.int32)
    return OocIVFFlat(index.centroids, index.slot_ids, norms,
                      index.cent_slots, slot_centroid,
                      index.list_sizes, index.metric, index.nprobe,
                      store)


# --------------------------------------------------------------------- #
# programs (profiled_jit: the serve warmup proof sees every compile)
# --------------------------------------------------------------------- #
def _ooc_probe_impl(centroids, cent_slots, q, nprobe, select_impl=None):
    """Probe + per-query slot-list compaction, device side.  Identical
    probe selection to the resident `_probe_scan_search` (same
    ``expanded_sq_dists`` + ``select_k`` + valid-first stable sort), so
    the ooc arm probes exactly the lists the resident arm would."""
    qn = jnp.sum(q * q, axis=1)
    qc = expanded_sq_dists(q, centroids)
    _, probes = select_k(qc, nprobe, select_min=True, impl=select_impl)
    nq = q.shape[0]
    slots = cent_slots[probes].reshape(nq, -1)           # -1-padded
    _, slots = lax.sort(((slots < 0).astype(jnp.int32), slots),
                        dimension=1, num_keys=1, is_stable=True)
    return slots, qn


_OOC_PROBE_STATICS = ("nprobe", "select_impl")
_ooc_probe_jit = profiled_jit(
    name="ooc_probe", static_argnames=_OOC_PROBE_STATICS)(_ooc_probe_impl)


def _ooc_scan_impl(part_vecs, part_ids, slot_ids, slot_norms, q, qn,
                   slots, run_d, run_i, k, select_impl=None):
    """Scan ONE device-resident part (the hot set, or one staged tile)
    against every query's probed-slot list, folding into the running
    top-k.  Per-candidate arithmetic is byte-identical to the resident
    `_ivf_flat_search_impl` step: gathered (nq, cap, d) block feeding
    only the highest-precision einsum, precomputed norms.  Entries
    whose slot is not in this part map to -1 and are compacted away —
    each probed (query, slot) pair is scanned by exactly one part."""
    nq = q.shape[0]
    S = part_vecs.shape[0]
    n_slots = slot_ids.shape[0]
    # slot id -> position in this part (scatter; pad part entries dump
    # into the n_slots overflow cell, which is then FORCED back to -1:
    # it must read as "absent" both for pad tiles and for the invalid
    # probed-slot entries that look up through it)
    pos = jnp.full((n_slots + 1,), -1, jnp.int32)
    pos = pos.at[jnp.where(part_ids >= 0, part_ids, n_slots)].set(
        jnp.arange(S, dtype=jnp.int32))
    pos = pos.at[n_slots].set(-1)
    sp = pos[jnp.where(slots >= 0, slots, n_slots)]      # (nq, P)
    # valid-first compaction as ONE stable variadic sort (the resident
    # scan's idiom): preserves probe order among the entries this part
    # holds
    _, sp, sl = lax.sort(
        ((sp < 0).astype(jnp.int32), sp, jnp.where(slots >= 0, slots, 0)),
        dimension=1, num_keys=1, is_stable=True)
    n_live = jnp.max(jnp.sum(sp >= 0, axis=1))
    dt = run_d.dtype

    def body(j, carry):
        rd, ri = carry
        valid = sp[:, j] >= 0
        spx = jnp.where(valid, sp[:, j], 0)
        slx = jnp.where(valid, sl[:, j], 0)
        vecs = part_vecs[spx]                            # (nq, cap, d)
        ids = slot_ids[slx]                              # (nq, cap)
        dist = (qn[:, None] + slot_norms[slx]
                - 2.0 * jnp.einsum("nd,ncd->nc", q, vecs,
                                   precision="highest"))
        ids = jnp.where(valid[:, None], ids, -1)
        dist = jnp.where(ids >= 0, jnp.maximum(dist, 0.0),
                         jnp.inf).astype(dt)
        cat_d = jnp.concatenate([rd, dist], axis=1)
        cat_i = jnp.concatenate([ri, ids], axis=1)
        return select_k(cat_d, k, select_min=True, values=cat_i,
                        impl=select_impl)

    return lax.fori_loop(0, n_live, body, (run_d, run_i))


_OOC_SCAN_STATICS = ("k", "select_impl")
_ooc_scan_jit = profiled_jit(
    name="ooc_scan", static_argnames=_OOC_SCAN_STATICS)(_ooc_scan_impl)
# donating twin for STAGED TILES only: a tile is pool-owned fresh
# storage, so the scan may recycle it; the hot set is persistent shared
# state and must go through the non-donating wrapper
_ooc_scan_jit_donated = profiled_jit(
    name="ooc_scan_donated", static_argnames=_OOC_SCAN_STATICS,
    donate_argnames=("part_vecs",))(_ooc_scan_impl)

# one pool-labeled counter constructor for the whole tier — the
# hit/miss families here must never skew from the pool's h2d families
_tile_counter = _pool_counter


# --------------------------------------------------------------------- #
# search driver
# --------------------------------------------------------------------- #
def ooc_ivf_flat_search(ooc: OocIVFFlat, queries, k: int,
                        nprobe: Optional[int] = None, *,
                        pool: TilePool,
                        hot: Optional[Tuple] = None,
                        delta=None,
                        donate_queries: bool = False,
                        select_impl: Optional[str] = None,
                        overlap: bool = True,
                        probe_hook=None,
                        force_rounds: int = 0,
                        handle=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Search the out-of-core index (module doc).

    ``hot`` is ``(hot_vecs (H, cap, d) device, hot_ids (H,) int32
    device, hot_mask (n_slots,) bool numpy)`` or None (everything
    streams).  ``overlap=False`` is the synchronous-prefetch baseline:
    each tile's transfer completes before its scan starts and the
    previous scan is drained first — the arm the bench measures the
    double-buffering win against.  ``probe_hook(distinct_slots,
    query_counts)`` feeds the caller's promotion counters.  ``force_rounds`` pads the tile
    loop with empty tiles (warmup: compiles the tile-scan executables
    even when the probed set happens to be fully hot).

    ``donate_queries`` donates the query buffer to the delta-merge twin
    only (the last consumer when a delta rides along); the streamed arm
    always donates the *staged tiles* instead — that is where the
    buffer traffic is.
    """
    q = jnp.asarray(queries)
    nprobe = ooc.nprobe if nprobe is None else nprobe
    nprobe = _validate_nprobe("ooc_ivf_flat_search", nprobe,
                              int(ooc.centroids.shape[0]))
    metric = DistanceType(int(ooc.metric))
    slots, qn = _ooc_probe_jit(ooc.centroids, ooc.cent_slots, q,
                               nprobe, select_impl=select_impl)
    # the ONE D2H sync: per-query probed slot ids (a few KB)
    slots_np = np.asarray(slots)
    distinct, dcounts = np.unique(slots_np[slots_np >= 0],
                                  return_counts=True)
    if hot is not None and hot[0].shape[0]:
        hot_mask = hot[2]
        cold = distinct[~hot_mask[distinct]]
    else:
        hot = None
        cold = distinct
    hits = int(distinct.size - cold.size)
    if hits:
        _tile_counter("raft_tpu_tile_hits_total",
                      "probed slots served from the device-resident "
                      "hot set", pool.name).inc(hits)
    if cold.size:
        _tile_counter("raft_tpu_tile_misses_total",
                      "probed slots streamed from the host store",
                      pool.name).inc(int(cold.size))
    if probe_hook is not None:
        probe_hook(distinct, dcounts)

    T = pool.tile_slots
    chunks = [cold[i:i + T] for i in range(0, int(cold.size), T)]
    while len(chunks) < force_rounds:
        chunks.append(np.empty(0, np.int64))

    nq = q.shape[0]
    dtp = jnp.result_type(q.dtype, jnp.float32)
    run = (jnp.full((nq, k), jnp.inf, dtp),
           jnp.full((nq, k), -1, jnp.int32))
    with default_profiler().span("ooc.scan", layer="ooc"):
        if hot is not None:
            run = _ooc_scan_jit(hot[0], hot[1], ooc.slot_ids,
                                ooc.slot_norms, q, qn, slots,
                                run[0], run[1], k,
                                select_impl=select_impl)
        staged = None
        try:
            if overlap and chunks:
                # double buffering: the first transfer overlaps the
                # hot scan when there is one; later transfers overlap
                # the previous tile's scan
                staged = pool.stage(ooc.store, chunks[0],
                                    hidden=hot is not None)
            for r in range(len(chunks)):
                if not overlap:
                    # synchronous baseline: drain the running scan,
                    # then transfer, then scan — nothing overlaps by
                    # design
                    jax.block_until_ready(run)
                    staged = pool.stage(ooc.store, chunks[r],
                                        hidden=False)
                # the scan still being in flight at the take is what
                # makes the remaining transfer wait *hidden* wall time
                vecs, ids_d = pool.take(staged,
                                        busy=not run[0].is_ready())
                staged = None
                run = _ooc_scan_jit_donated(vecs, ids_d, ooc.slot_ids,
                                            ooc.slot_norms, q, qn,
                                            slots, run[0], run[1], k,
                                            select_impl=select_impl)
                if overlap and r + 1 < len(chunks):
                    staged = pool.stage(ooc.store, chunks[r + 1],
                                        hidden=True)
        except BaseException:
            # a scan/stage failure mid-stream must not strand a
            # staged-not-taken tile's budget charge (the serve worker
            # relays the error and keeps dispatching)
            if staged is not None:
                pool.discard(staged)
            raise
    dist, ids = run
    if metric in (D.L2SqrtExpanded, D.L2SqrtUnexpanded):
        dist = jnp.sqrt(dist)
    out = (dist, ids)
    if delta is not None:
        out = _merge_delta(out, delta, q, k, metric, donate_queries)
    record_on_handle(handle, *out)
    return out


# --------------------------------------------------------------------- #
# hot set / maintenance plumbing
# --------------------------------------------------------------------- #
def materialize_hot(ooc: OocIVFFlat, hot_ids: np.ndarray, *,
                    pool_name: str = "ooc",
                    device=None) -> Tuple[jnp.ndarray, jnp.ndarray,
                                          np.ndarray]:
    """Commit the slots in ``hot_ids`` to device as the hot-set block;
    returns ``(hot_vecs, hot_ids_device, hot_mask)``.  Budget-bounded
    by construction (the caller sized H from its byte budget); counted
    as H2D traffic like any other stream."""
    ids = np.asarray(hot_ids, np.int32).ravel()
    expects(ids.size == 0 or (ids.min() >= 0
                              and ids.max() < ooc.n_slots),
            "materialize_hot: slot ids out of range")
    host = ooc.store[ids]
    if device is not None:
        vecs = jax.device_put(host, device)  # ooc-resident-ok (budget-bounded hot set)
        ids_d = jax.device_put(ids, device)  # ooc-resident-ok (budget-bounded hot set)
    else:
        vecs = jax.device_put(host)  # ooc-resident-ok (budget-bounded hot set)
        ids_d = jax.device_put(ids)  # ooc-resident-ok (budget-bounded hot set)
    _tile_counter("raft_tpu_h2d_bytes_total",
                  "bytes streamed host-to-device by tile pools",
                  pool_name).inc(int(host.nbytes) + int(ids.nbytes))
    mask = np.zeros(ooc.n_slots, bool)
    mask[ids] = True
    return vecs, ids_d, mask


def ooc_reconstruct(ooc: OocIVFFlat) -> Tuple[np.ndarray, np.ndarray]:
    """``(vectors, ids)`` from the host store (valid rows, slot order)
    — the out-of-core twin of
    :func:`~raft_tpu.spatial.ann.ivf_flat_reconstruct`; entirely
    host-side."""
    ids = np.asarray(ooc.slot_ids).reshape(-1)
    mask = ids >= 0
    vecs = ooc.store.reshape(-1, ooc.store.shape[-1])
    return vecs[mask], ids[mask].astype(np.int64)


def ooc_extend(ooc: OocIVFFlat, vectors, ids, *,
               slot_multiple: int = 64) -> OocIVFFlat:
    """Fold new rows into the out-of-core index — the compaction half
    of streaming ingestion, host-side: same nearest-existing-centroid
    assignment and slot-layout rounding as
    :func:`~raft_tpu.spatial.ann.ivf_flat_extend`
    (``_extend_slot_layout`` is literally shared), but the rebuilt slot
    store is assembled in numpy and NEVER materialized on device — the
    whole point of the tier.  Only the small metadata (ids, norms,
    cent_slots) is re-committed."""
    new_vecs = np.asarray(vectors, ooc.store.dtype)
    expects(new_vecs.ndim == 2
            and new_vecs.shape[1] == ooc.store.shape[2],
            "ooc_extend: expected (rows, %d) vectors, got %r",
            int(ooc.store.shape[2]), tuple(new_vecs.shape))
    new_ids = np.asarray(ids, np.int64).ravel()
    expects(new_ids.shape[0] == new_vecs.shape[0],
            "ooc_extend: %d ids for %d vectors",
            new_ids.shape[0], new_vecs.shape[0])
    nlist = int(ooc.centroids.shape[0])
    cap = ooc.cap

    old_vecs, old_ids = ooc_reconstruct(ooc)
    old_labels = np.repeat(ooc.slot_centroid, cap)[
        np.asarray(ooc.slot_ids).reshape(-1) >= 0]
    if new_vecs.shape[0]:
        new_labels = np.asarray(_assign_labels(jnp.asarray(new_vecs),
                                               ooc.centroids))
        all_vecs = np.concatenate([old_vecs, new_vecs], axis=0)
        all_ids = np.concatenate([old_ids, new_ids])
        labels = np.concatenate(
            [old_labels.astype(np.int64), new_labels.astype(np.int64)])
    else:
        all_vecs, all_ids = old_vecs, old_ids
        labels = old_labels.astype(np.int64)

    slot_rows, slot_cent, cent_slots, counts = _extend_slot_layout(
        labels, nlist, cap, slot_multiple)
    gather = np.clip(slot_rows, 0, None)
    store = all_vecs[gather]
    store[slot_rows < 0] = 0
    slot_ids_np = np.where(slot_rows >= 0,
                           all_ids[gather].astype(np.int32), -1)
    # einsum, not (store * store).sum(-1): the elementwise square of a
    # store-sized array would transiently double host memory
    norms = np.einsum("scd,scd->sc", store, store)
    return OocIVFFlat(ooc.centroids,
                      jnp.asarray(slot_ids_np.astype(np.int32)),
                      jnp.asarray(norms),
                      jnp.asarray(cent_slots),
                      slot_cent.astype(np.int32),
                      jnp.asarray(counts, jnp.int32),
                      ooc.metric, ooc.nprobe, store)
