"""k-selection: per-row top-k of a key matrix.

Reference: ``select_k`` (cpp/include/raft/spatial/knn/knn.hpp:90)
dispatching into the forked-FAISS warp/block select kernels
(detail/selection_faiss.cuh:131-160, detail/warp_select_faiss.cuh,
detail/block_select_faiss.cuh) — a register-heap per warp merged through
shared memory, specialised for k ≤ {32,64,128,256,512,1024}.

TPU re-design: there are no warp shuffles or per-thread heaps on a
systolic/vector machine; the efficient shapes are (a) XLA's native sorted
``TopK`` (bitonic-style, k-specialised) and (b) on real TPU hardware the
``approx_max_k`` MIPS instruction path with recall=1.0.  Both keep the
whole row in VMEM-resident vectors; for very wide rows XLA tiles
internally.  We dispatch to ``lax.top_k`` (exact, sorted, stable toward
smaller index on ties — the same tie rule as the reference's heap with
sequential insertion) and translate min-selection by key negation.

``select_k`` is THE building block for kNN merge and ANN list scans, so it
accepts an optional payload (``values``) to carry indices through
selection, mirroring the (key, value) pairs of the reference heaps.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects


def select_k(
    keys: jnp.ndarray,
    k: int,
    select_min: bool = True,
    values: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Select the k smallest (or largest) keys per row.

    Parameters
    ----------
    keys:
        (m, n) key matrix (e.g. distances).
    k:
        Number of entries to keep per row (k <= n).
    select_min:
        True → k smallest (distance semantics); False → k largest
        (inner-product semantics).  Reference knn.hpp:90 ``select_min``.
    values:
        Optional (m, n) payload carried through selection (e.g. global
        ids).  Defaults to the column index, matching the reference's
        identity-value path.

    Returns
    -------
    (out_keys, out_values): (m, k) selected keys, sorted best-first, and
    their payloads (int32 column indices when ``values`` is None).
    """
    expects(keys.ndim == 2, "select_k: 2-D keys required")
    n = keys.shape[1]
    expects(0 < k <= n, "select_k: k=%d out of range for n=%d", k, n)

    sel = -keys if select_min else keys
    top_vals, top_idx = lax.top_k(sel, k)
    out_keys = -top_vals if select_min else top_vals
    if values is None:
        return out_keys, top_idx.astype(jnp.int32)
    out_values = jnp.take_along_axis(values, top_idx, axis=1)
    return out_keys, out_values
