"""k-selection: per-row top-k of a key matrix.

Reference: ``select_k`` (cpp/include/raft/spatial/knn/knn.hpp:90)
dispatching into the forked-FAISS warp/block select kernels
(detail/selection_faiss.cuh:131-160, detail/warp_select_faiss.cuh,
detail/block_select_faiss.cuh) — a register-heap per warp merged through
shared memory, specialised for k ≤ {32,64,128,256,512,1024}.

TPU re-design: there are no warp shuffles or per-thread heaps on a
systolic/vector machine; the efficient shapes are (a) XLA's native sorted
``TopK`` (bitonic-style, k-specialised) and (b) the TPU
``approx_max_k`` PartialReduce instruction path, exact at
``recall_target=1.0`` + ``aggregate_to_topk`` and typically faster on
wide rows.  Both keep the whole row in VMEM-resident vectors; for very
wide rows XLA tiles internally.  Min-selection is key negation.

Implementation choice (``impl``): ``"topk"`` (default) is ``lax.top_k``
— exact, sorted, stable toward smaller index on ties (the same tie rule
as the reference's heap with sequential insertion).  ``"approx"`` is
``lax.approx_max_k`` — exact in *membership* at recall 1.0 but with no
tie-order guarantee.  The default is the ``select_impl`` knob of
:mod:`raft_tpu.config` (env alias ``RAFT_TPU_SELECT_IMPL``; the
executable-cache caveat — knobs are consumed at trace time and cannot
reach already-compiled shapes — is documented there, once).  The bench
measures the impls on hardware and reports the winner rather than
assuming.

``select_k`` is THE building block for kNN merge and ANN list scans, so it
accepts an optional payload (``values``) to carry indices through
selection, mirroring the (key, value) pairs of the reference heaps.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.utils import ceildiv


def chunked_top_k(sel: jnp.ndarray, k: int,
                  chunk: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact per-row top-k (largest) as a merge tree of SMALL top-ks.

    One wide ``lax.top_k`` over (rows, W) is a sort-shaped selection
    whose cross-lane traffic grows with W.  This formulation splits each
    row into ``W/chunk`` chunks, top-ks every chunk in one batched call
    (the batch maps onto sublanes; the sort network spans only ``chunk``
    lanes), then pairwise-merges sorted k-lists — each merge round is a
    single batched top-k over 2k-wide rows.  Same results as
    ``lax.top_k`` up to tie order (ties broken toward the smaller index
    *within* the merge tree's bracket, not globally).

    The reference hits the identical problem shape on GPUs and answers
    with register-heap warp selection (knn.hpp:90 →
    detail/warp_select_faiss.cuh); a TPU has no warps, but it DOES have
    cheap batched small sorts — this is that answer.  Candidate for the
    tile-scan kNN driver where selection, not the distance matmul,
    bounds throughput (measured: the (4096, 8192) k=100 top_k costs
    ~400x the tile's MXU time on v5e).
    """
    nq, w = sel.shape
    if w <= max(2 * k, chunk):
        return lax.top_k(sel, k)
    c = ceildiv(w, chunk)
    pad = c * chunk - w
    if pad:
        # pads must NEVER outrank a genuine entry: -inf (not finfo.min,
        # which BEATS genuine -inf keys) for floats; ints get their min
        # and rely on the final clamp
        sel = jnp.pad(sel, ((0, 0), (0, pad)),
                      constant_values=_pad_sentinel(sel.dtype))
    kc = min(k, chunk)
    x = sel.reshape(nq, c, chunk)
    vals, idx = lax.top_k(x, kc)                    # (nq, c, kc) batched
    idx = idx + (jnp.arange(c) * chunk)[None, :, None]
    while c > 1:
        if c % 2:
            vals = jnp.pad(vals, ((0, 0), (0, 1), (0, 0)),
                           constant_values=_pad_sentinel(vals.dtype))
            idx = jnp.pad(idx, ((0, 0), (0, 1), (0, 0)))
            c += 1
        vals = vals.reshape(nq, c // 2, 2 * kc)
        idx = idx.reshape(nq, c // 2, 2 * kc)
        kc2 = min(k, 2 * kc)
        # one variadic sort (descending via the order flip) replaces
        # top_k + take_along_axis: the per-row gather lowers to a
        # serial scalar loop on TPU while a 2kc-lane sort with the ids
        # as a carried operand stays vector-shaped (same finding as the
        # tile-scan merge, tiled_knn.py).  _flip (not jnp.negative):
        # integer negation wraps INT_MIN onto itself, which would rank
        # the odd-round pad sentinel FIRST; ~x is overflow-free.
        fv, idx = lax.sort((_flip(vals), idx), dimension=2)
        vals = _flip(fv[:, :, :kc2])
        idx = idx[:, :, :kc2]
        kc = kc2
        c //= 2
    # pads can only surface when a row has fewer than k entries above
    # the sentinel (all-(-inf) tails); clamp keeps such deficit slots
    # in-range (arbitrary id, sentinel value) instead of fabricating
    # out-of-range ids that a payload gather would silently clamp
    return vals[:, 0, :k], jnp.minimum(idx[:, 0, :k], w - 1)


def _pad_sentinel(dtype):
    return (-jnp.inf if jnp.issubdtype(dtype, jnp.floating)
            else jnp.iinfo(dtype).min)


def _flip(x):
    """Order-reversing involution for ascending-sort-as-descending.

    ``jnp.negative`` would do for floats (-(-inf) = +inf) but wraps
    INT_MIN onto itself for two's-complement ints; bitwise NOT
    (~x = -x - 1) is strictly order-reversing with no overflow and maps
    ``_pad_sentinel``'s iinfo.min to iinfo.max (sorts last, as a pad
    must)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.negative(x)
    return jnp.bitwise_not(x)


def _resolve_impl(impl: Optional[str], *, n: Optional[int] = None,
                  k: Optional[int] = None, dtype=None) -> str:
    """Default + validation for the select impl (shared by
    :func:`top_k_rows`, :func:`select_k`, and the tile-scan driver):
    one call into the candidate registry
    (:func:`raft_tpu.core.tuning.resolve`), which walks the config
    ladder — override → configure → env (RAFT_TPU_SELECT_IMPL) →
    tuning table (shape-class on (n, k)) → default — and owns the
    candidate whitelist + legality (caveats documented in
    :mod:`raft_tpu.config`, once)."""
    return tuning.resolve("select_impl", impl, site="select_k",
                          dtype=dtype, n=n, k=k)


def top_k_rows(sel: jnp.ndarray, k: int,
               impl: Optional[str] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Raw per-row top-k (largest) with impl dispatch (module doc).
    Shared by :func:`select_k` and the tile-scan kNN driver.

    ``"chunked"`` is :func:`chunked_top_k` — exact, tie order local to
    its merge bracket.  ``"pallas"`` is the fused threshold-gated
    selection kernel (:mod:`raft_tpu.ops.select_tile`; float keys,
    k <= 128) — exact in value, deficit slots clamped, tie ids may
    differ from ``top_k``'s smallest-index rule.  ``"approx95"`` is the
    one deliberately APPROXIMATE mode (recall_target 0.95): unlike
    ``"approx"``/recall
    1.0 — whose partial reduce cannot drop anything and degenerates to
    the same sort as ``top_k`` (measured identical QPS on v5e) — it
    genuinely shrinks the reduction width.  Exact-contract callers (the
    public kNN/ANN paths) never default to approx95; it exists for
    consumers that opt into recall-for-speed, and the bench reports its
    measured recall next to its QPS."""
    impl = _resolve_impl(impl, n=sel.shape[1], k=k, dtype=sel.dtype)
    if impl == "pallas":
        # fused threshold-gated selection kernel (ops/select_tile.py):
        # the kernel selects SMALLEST, this contract is largest —
        # negate in, negate out.  Float keys and k <= 128 only (the
        # kernel errors otherwise, mirroring the explicit-pallas rule
        # of fused_l2_knn).
        from raft_tpu.ops.select_tile import select_tile

        vals, idx = select_tile(jnp.negative(sel), k)
        return jnp.negative(vals), idx
    if impl == "chunked":
        return chunked_top_k(sel, k)
    if impl == "approx95":
        return lax.approx_max_k(sel, k, recall_target=0.95,
                                aggregate_to_topk=True)
    if impl == "approx":
        return lax.approx_max_k(sel, k, recall_target=1.0,
                                aggregate_to_topk=True)
    return lax.top_k(sel, k)


def select_k(
    keys: jnp.ndarray,
    k: int,
    select_min: bool = True,
    values: Optional[jnp.ndarray] = None,
    impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Select the k smallest (or largest) keys per row.

    Parameters
    ----------
    keys:
        (m, n) key matrix (e.g. distances).
    k:
        Number of entries to keep per row (k <= n).
    select_min:
        True → k smallest (distance semantics); False → k largest
        (inner-product semantics).  Reference knn.hpp:90 ``select_min``.
    values:
        Optional (m, n) payload carried through selection (e.g. global
        ids).  Defaults to the column index, matching the reference's
        identity-value path.
    impl:
        "topk" | "approx" | None (env/default; module doc).

    Returns
    -------
    (out_keys, out_values): (m, k) selected keys, sorted best-first, and
    their payloads (int32 column indices when ``values`` is None).
    """
    expects(keys.ndim == 2, "select_k: 2-D keys required")
    n = keys.shape[1]
    expects(0 < k <= n, "select_k: k=%d out of range for n=%d", k, n)

    impl = _resolve_impl(impl, n=n, k=k, dtype=keys.dtype)
    if values is None:
        sel = -keys if select_min else keys
        top_vals, top_idx = top_k_rows(sel, k, impl)
        out_keys = -top_vals if select_min else top_vals
        return out_keys, top_idx.astype(jnp.int32)
    if impl == "topk":
        # payload path: carry the payload THROUGH the selection as a
        # sort operand instead of gathering it afterwards —
        # take_along_axis over the full row width lowers to a serial
        # scalar-gather loop on TPU (measured r4: it dominated the
        # tile-scan kNN wall time), while a variadic sort keeps
        # everything vector-shaped.  lax.top_k lowers to a full sort on
        # TPU anyway, so the sort costs no more than the top_k it
        # replaces.  Sort key: ascending `keys` directly for
        # select_min; the overflow-free order flip of `keys` (not
        # integer negation, which wraps INT_MIN) for select-largest.
        skey = keys if select_min else _flip(keys)
        sorted_keys, out_values = lax.sort((skey, values), dimension=1)
        out_keys = (sorted_keys[:, :k] if select_min
                    else _flip(sorted_keys[:, :k]))
        return out_keys, out_values[:, :k]
    # non-default impls (approx*/chunked/pallas) pick their winners by
    # other means than a full sort; the payload must be fetched by a
    # row-wise gather (the cost the default path avoids)
    sel = -keys if select_min else keys
    top_vals, top_idx = top_k_rows(sel, k, impl)
    out_keys = -top_vals if select_min else top_vals
    out_values = jnp.take_along_axis(values, top_idx, axis=1)
    return out_keys, out_values
