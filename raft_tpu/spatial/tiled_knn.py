"""Shared tile-scan kNN driver.

The reference's fused kNN kernels (fused_l2_knn.cuh:196, and the
haversine variant haversine_distance.cuh:61) share one structure: stream
index tiles through fast memory, compute a distance tile, select top-k in
the tile, merge with the running top-k (the usePrevTopKs path).  This
module is that structure as a ``lax.scan``, parameterized by the per-tile
distance function — XLA pipelines the scan so tile t+1's distance
computation overlaps tile t's selection.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import tuning
from raft_tpu.core.error import expects
from raft_tpu.core.profiler import profiled, profiled_jit
from raft_tpu.core.utils import as_pytree_fn, ceildiv
from raft_tpu.spatial.select_k import _resolve_impl, top_k_rows


@profiled("spatial")
def tiled_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    tile_dist: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    tile_n: int = 8192,
    merge: Optional[str] = None,
    donate_queries: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k best (smallest-distance) index rows per query.

    ``tile_dist(queries, index_tile) -> (n_queries, tile_n)`` computes the
    distance tile; padding rows of the index are zeros and their distances
    are overridden to +inf here, so ``tile_dist`` need not handle them.

    STABLE IDENTITY REQUIRED for repeat calls: the scan body is jitted
    and ``tile_dist`` crosses the boundary via
    :func:`raft_tpu.core.utils.as_pytree_fn`, so the executable caches
    on the function's identity (plus operand shapes).  Pass a
    module-level function, a memoized factory product, or a
    ``tree_util.Partial`` over array args (see ``fused_l2_knn``); a
    closure defined per call recompiles the whole scan per call and
    grows the jit cache without bound.

    ``merge`` selects the per-tile selection strategy (default: the
    ``tile_merge`` knob of :mod:`raft_tpu.config`, env alias
    ``RAFT_TPU_TILE_MERGE`` — trace-time-consumption caveat documented
    there; pass ``merge`` explicitly to pin it per call):

    - ``"tile_topk"`` (default): top-k the tile (impl-dispatched, see
      :func:`~raft_tpu.spatial.select_k.top_k_rows`), then one 2k-wide
      variadic sort merges it into the running top-k.
    - ``"direct"``: no per-tile top-k — ONE variadic sort over the
      (k + tile_n)-wide concatenation of running top-k and raw tile.
      On backends where ``lax.top_k`` lowers to a full sort anyway
      (TPU), this does the same lane width once instead of
      sort(tile_n) + sort(2k); where top_k has a real partial
      implementation, ``tile_topk`` wins.  The bench ladder measures
      both on hardware.

    ``donate_queries=True`` routes the call through the DONATING twin
    of the scan executable (identical program, ``donate_argnames=
    ("queries",)``): the queries buffer is consumed by the call and
    recycled — callers must own the buffer and not reuse it (the serve
    layer's padded batch is the intended consumer; docs/ZERO_COPY.md).
    The scan's (best_d, best_i) carry is aliased in place by XLA inside
    the program either way — donation extends that recycling to the
    input buffer itself.

    Returns (distances, indices): (n_queries, k) ascending, int32 ids.
    """
    n = index.shape[0]
    expects(0 < k <= n, "tiled_knn: k=%d out of range for n_index=%d", k, n)
    merge = tuning.resolve("tile_merge", merge, site="tiled_knn",
                           n=n, k=k, dtype=queries.dtype)
    # knobs resolved HERE (outside the jit) and passed static, so the
    # executable caches on their values; tile_dist crosses the boundary
    # as a pytree (fresh closures would otherwise retrace the whole
    # scan every call — the r5 retrace audit caught exactly that on
    # brute_force_knn's steady state)
    run = _tiled_knn_run_donated if donate_queries else _tiled_knn_run
    return run(index, queries, as_pytree_fn(tile_dist),
               k=k, tile_n=max(k, min(tile_n, n)),
               merge=merge,
               select_impl=_resolve_impl(
                   None, n=max(k, min(tile_n, n)), k=k,
                   dtype=queries.dtype))


def _tiled_knn_body(index, queries, tile_dist, k, tile_n, merge,
                    select_impl):
    n = index.shape[0]
    nq = queries.shape[0]
    n_tiles = ceildiv(n, tile_n)
    n_pad = n_tiles * tile_n
    x_p = jnp.pad(index, ((0, n_pad - n), (0, 0)))
    valid = jnp.arange(n_pad) < n

    def step(carry, tile_idx):
        best_d, best_i = carry
        j0 = tile_idx * tile_n
        x_t = lax.dynamic_slice_in_dim(x_p, j0, tile_n, axis=0)
        v_t = lax.dynamic_slice_in_dim(valid, j0, tile_n, axis=0)
        d = jnp.where(v_t[None, :], tile_dist(queries, x_t), jnp.inf)
        if merge == "direct":
            # one (k + tile_n)-wide variadic sort: raw tile + running
            # top-k in a single pass (module doc)
            cat_d = jnp.concatenate([best_d, d], axis=1)
            # astype: under x64 the scanned tile_idx is int64 and would
            # widen the id carry out of its int32 type
            tid = (j0 + jnp.arange(tile_n)).astype(jnp.int32)[None, :]
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(tid, d.shape)], axis=1)
        else:
            # wide tile selection dispatches impl (top_k vs the TPU
            # approx_max_k instruction at recall 1.0 — see select_k
            # module doc); the narrow 2k merge below stays a sort
            t_vals, t_idx = top_k_rows(-d, k, impl=select_impl)
            t_idx = (j0 + t_idx).astype(jnp.int32)
            cat_d = jnp.concatenate([best_d, -t_vals], axis=1)
            cat_i = jnp.concatenate([best_i, t_idx], axis=1)
        # merge via variadic sort, indices carried as a sort operand.
        # NOT top_k + take_along_axis: the per-row gather lowers to a
        # serial scalar loop on TPU and dominated the whole scan
        # (measured r4: ~94% of the 100k-shape wall time), while the
        # sort stays vector-shaped.  num_keys=2 makes the tie rule
        # exactly lexicographic (distance, then smaller index) — the
        # reference heap's insertion-order rule.
        m_d, m_i = lax.sort((cat_d, cat_i), dimension=1, num_keys=2)
        return (m_d[:, :k], m_i[:, :k]), None

    init = (jnp.full((nq, k), jnp.inf,
                     dtype=jnp.result_type(queries.dtype, jnp.float32)),
            jnp.full((nq, k), jnp.iinfo(jnp.int32).max, dtype=jnp.int32))
    (best_d, best_i), _ = lax.scan(step, init, jnp.arange(n_tiles))
    return best_d, best_i


_STATICS = ("k", "tile_n", "merge", "select_impl")
_tiled_knn_run = profiled_jit(
    name="tiled_knn", static_argnames=_STATICS)(_tiled_knn_body)
# the donating twin (docs/ZERO_COPY.md): same program, the queries
# buffer is consumed and recycled.  A separate wrapper (and stats
# name), never a runtime flag — a donating and a non-donating
# executable must not share a compile-cache slot
_tiled_knn_run_donated = profiled_jit(
    name="tiled_knn_donated", static_argnames=_STATICS,
    donate_argnames=("queries",))(_tiled_knn_body)
