"""Haversine (great-circle) distance and kNN.

Reference: cpp/include/raft/spatial/knn/detail/haversine_distance.cuh —
``compute_haversine`` (:38) gives ``2·asin(√(sin²(Δlat/2) +
cos(lat₁)cos(lat₂)sin²(Δlon/2)))`` on radian coordinates, and
``haversine_knn_kernel`` (:61) pairs it with a block-select top-k.

TPU re-design: the 2-D feature dimension makes this VPU-bound elementwise
work — broadcast the (m, 1, 2) × (1, n, 2) trig terms and reduce with
``select_k``.  For large n the kNN path tiles over index rows the same
way as :mod:`raft_tpu.spatial.fused_l2_knn`.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.utils import ceildiv


def haversine_distances(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """All-pairs haversine distance between (m, 2) and (n, 2) radian
    lat/lon rows (reference compute_haversine, haversine_distance.cuh:38)."""
    expects(x.ndim == 2 and x.shape[1] == 2 and y.ndim == 2 and y.shape[1] == 2,
            "haversine distance requires 2 dimensions (latitude / longitude).")
    sin_lat = jnp.sin(0.5 * (x[:, None, 0] - y[None, :, 0]))
    sin_lon = jnp.sin(0.5 * (x[:, None, 1] - y[None, :, 1]))
    rdist = sin_lat**2 + jnp.cos(x[:, None, 0]) * jnp.cos(y[None, :, 0]) * sin_lon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(rdist, 0.0, 1.0)))


def haversine_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    tile_n: int = 8192,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows per query under haversine distance
    (reference haversine_knn, haversine_distance.cuh:120).

    Returns (distances, indices) of shape (n_queries, k).
    """
    n = index.shape[0]
    expects(0 < k <= n, "haversine_knn: k=%d out of range for n_index=%d", k, n)
    nq = queries.shape[0]
    tile_n = max(k, min(tile_n, n))
    n_tiles = ceildiv(n, tile_n)
    n_pad = n_tiles * tile_n
    x_p = jnp.pad(index, ((0, n_pad - n), (0, 0)))
    valid = jnp.arange(n_pad) < n

    def step(carry, tile_idx):
        best_d, best_i = carry
        j0 = tile_idx * tile_n
        x_t = lax.dynamic_slice_in_dim(x_p, j0, tile_n, axis=0)
        v_t = lax.dynamic_slice_in_dim(valid, j0, tile_n, axis=0)
        d = haversine_distances(queries, x_t)
        d = jnp.where(v_t[None, :], d, jnp.inf)
        kk = min(k, tile_n)
        t_vals, t_idx = lax.top_k(-d, kk)
        t_idx = (j0 + t_idx).astype(jnp.int32)
        cat_d = jnp.concatenate([best_d, -t_vals], axis=1)
        cat_i = jnp.concatenate([best_i, t_idx], axis=1)
        m_vals, m_pos = lax.top_k(-cat_d, k)
        return (-m_vals, jnp.take_along_axis(cat_i, m_pos, axis=1)), None

    init = (jnp.full((nq, k), jnp.inf, dtype=jnp.result_type(queries.dtype, jnp.float32)),
            jnp.full((nq, k), jnp.iinfo(jnp.int32).max, dtype=jnp.int32))
    (best_d, best_i), _ = lax.scan(step, init, jnp.arange(n_tiles))
    return best_d, best_i
