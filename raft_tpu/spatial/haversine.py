"""Haversine (great-circle) distance and kNN.

Reference: cpp/include/raft/spatial/knn/detail/haversine_distance.cuh —
``compute_haversine`` (:38) gives ``2·asin(√(sin²(Δlat/2) +
cos(lat₁)cos(lat₂)sin²(Δlon/2)))`` on radian coordinates, and
``haversine_knn_kernel`` (:61) pairs it with a block-select top-k.

TPU re-design: the 2-D feature dimension makes this VPU-bound elementwise
work — broadcast the (m, 1, 2) × (1, n, 2) trig terms; the kNN path runs
on the shared tile-scan driver (:mod:`raft_tpu.spatial.tiled_knn`).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.spatial.tiled_knn import tiled_knn


def haversine_distances(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """All-pairs haversine distance between (m, 2) and (n, 2) radian
    lat/lon rows (reference compute_haversine, haversine_distance.cuh:38)."""
    expects(x.ndim == 2 and x.shape[1] == 2 and y.ndim == 2 and y.shape[1] == 2,
            "haversine distance requires 2 dimensions (latitude / longitude).")
    sin_lat = jnp.sin(0.5 * (x[:, None, 0] - y[None, :, 0]))
    sin_lon = jnp.sin(0.5 * (x[:, None, 1] - y[None, :, 1]))
    rdist = sin_lat**2 + jnp.cos(x[:, None, 0]) * jnp.cos(y[None, :, 0]) * sin_lon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(rdist, 0.0, 1.0)))


def haversine_knn(
    index: jnp.ndarray,
    queries: jnp.ndarray,
    k: int,
    tile_n: int = 8192,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest index rows per query under haversine distance
    (reference haversine_knn, haversine_distance.cuh:120).

    Returns (distances, indices) of shape (n_queries, k).
    """
    expects(queries.ndim == 2 and queries.shape[1] == 2,
            "haversine distance requires 2 dimensions (latitude / longitude).")
    return tiled_knn(index, queries, k, haversine_distances, tile_n=tile_n)
