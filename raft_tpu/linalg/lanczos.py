"""Restarted Lanczos eigensolver.

Reference: cpp/include/raft/linalg/lanczos.hpp (1,478 LoC) —
``computeSmallestEigenvectors`` (:754,1033) / ``computeLargestEigenvectors``
(:1141): Lanczos iteration (SpMV + dot/axpy/nrm2, :88-180), host LAPACK
``steqr`` on the tridiagonal, Francis-QR implicit restarts (:388,546).

TPU redesign: instead of translating the scalar-heavy CUDA iteration, we run
*thick-restart* Lanczos with **full reorthogonalization**: basis expansion is
a sequence of matvecs plus (n×m)ᵀ(n×1) projections — tall-skinny matmuls that
map straight onto the MXU — and the small (m×m) projected problem is solved
with a dense symmetric eigensolver (the ``steqr`` role).  Full
reorthogonalization costs a little more FLOP but removes the ghost-eigenvalue
pathology the reference's restart machinery exists to fight, and FLOPs are
what a TPU has.

The matrix is supplied as a callable ``mv(x) -> A @ x`` (the
``sparse_matrix_t::mv`` interface, reference spectral/matrix_wrappers.hpp:180)
or as a dense array.
"""

from __future__ import annotations

from typing import Callable, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.debug import check_finite
from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle

Operator = Union[jnp.ndarray, Callable[[jnp.ndarray], jnp.ndarray]]


def _as_mv(a: Operator) -> Callable[[jnp.ndarray], jnp.ndarray]:
    if callable(a):
        return a
    return lambda x: a @ x


def _expand_basis(mv, v_basis: jnp.ndarray, av_basis: jnp.ndarray,
                  start: int, stop: int, key: jax.Array):
    """Grow an orthonormal basis from ``start`` to ``stop`` columns.

    v_basis is (n, m); columns [0, start) are already orthonormal and column
    ``start`` holds the (normalized) next direction.  av_basis caches
    ``mv(v_j)`` for every processed column so Rayleigh-Ritz never recomputes
    a matvec.  Each step: w = A v_j, orthogonalize against ALL previous
    columns twice (classical Gram-Schmidt, two passes — MXU-shaped), then
    normalize into column j+1.  If the Krylov space is exhausted (w ~ 0) the
    next column is re-seeded with a random direction orthogonal to the
    basis, keeping the basis orthonormal instead of fabricating zero
    columns (which would produce spurious zero-residual Ritz pairs).
    """
    n = v_basis.shape[0]

    def orthonormalize(w, vb):
        for _ in range(2):
            w = w - vb @ (vb.T @ w)
        return w, jnp.linalg.norm(w)

    def step(j, carry):
        vb, ab = carry
        v_j = jax.lax.dynamic_slice_in_dim(vb, j, 1, axis=1)[:, 0]
        av = mv(v_j)
        ab = jax.lax.dynamic_update_slice_in_dim(ab, av[:, None], j, axis=1)
        w, nrm = orthonormalize(av, vb)

        def krylov_next(_):
            return w / jnp.where(nrm > 0, nrm, 1.0)

        def reseed(_):
            r = jax.random.uniform(
                jax.random.fold_in(key, j), (n,), dtype=vb.dtype,
                minval=-1.0, maxval=1.0)
            r, rn = orthonormalize(r, vb)
            return r / jnp.maximum(rn, 1e-30)

        w = jax.lax.cond(nrm > 1e-10, krylov_next, reseed, operand=None)
        vb = jax.lax.dynamic_update_slice_in_dim(vb, w[:, None], j + 1, axis=1)
        return vb, ab

    return jax.lax.fori_loop(start, stop, step, (v_basis, av_basis))


def _ritz(v_basis: jnp.ndarray, av_basis: jnp.ndarray, m: int):
    """Rayleigh-Ritz on the first m columns using cached A@V."""
    v = v_basis[:, :m]
    av = av_basis[:, :m]
    h = v.T @ av
    h = 0.5 * (h + h.T)
    theta, s = jnp.linalg.eigh(h)
    y = v @ s
    # residual norms ||A y - theta y|| per Ritz pair
    r = av @ s - y * theta[None, :]
    resid = jnp.linalg.norm(r, axis=0)
    return theta, y, s, resid


def _lanczos(
    a: Operator,
    n: int,
    k: int,
    which: str,
    ncv: int,
    max_restarts: int,
    tol: float,
    seed: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    # Krylov orthogonality is what convergence rests on: every matmul in
    # the solver (projections, re-orthogonalization, Ritz rotation, and
    # a matrix-operand mv) must run f32-faithful.  XLA's TPU default for
    # f32 matmuls is single-pass bf16 — enough orthogonality loss to
    # stall restarts — so pin the whole solver body.
    with jax.default_matmul_precision("highest"):
        return _lanczos_impl(a, n, k, which, ncv, max_restarts, tol, seed)


def _lanczos_impl(a, n, k, which, ncv, max_restarts, tol, seed):
    mv = _as_mv(a)
    expects(0 < k < n, "lanczos: need 0 < k < n (k=%d, n=%d)", k, n)
    m = min(max(ncv, 2 * k + 1), n)
    dtype = (a.dtype if hasattr(a, "dtype") else jnp.zeros(0).dtype)
    if not jnp.issubdtype(dtype, jnp.floating):
        dtype = jnp.float32

    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    v0 = jax.random.uniform(sub, (n,), dtype=dtype, minval=-1.0, maxval=1.0)
    v0 = v0 / jnp.linalg.norm(v0)

    v_basis = jnp.zeros((n, m), dtype=dtype).at[:, 0].set(v0)
    av_basis = jnp.zeros((n, m), dtype=dtype)
    n_iter = 0
    keep = jnp.arange(k)
    for restart in range(max_restarts):
        start = 1 if restart == 0 else k + 1
        key, sub = jax.random.split(key)
        v_basis, av_basis = _expand_basis(mv, v_basis, av_basis, start - 1, m - 1, sub)
        # matvec for the last column (the loop fills av only up to m-2)
        av_last = mv(v_basis[:, m - 1])
        av_basis = av_basis.at[:, m - 1].set(av_last)
        n_iter += m - start + 1
        theta, y, s, resid = _ritz(v_basis, av_basis, m)
        if which == "smallest":
            order = jnp.argsort(theta)
        else:
            order = jnp.argsort(-theta)
        keep = order[:k]
        max_resid = float(jnp.max(resid[keep]))
        scale = float(jnp.max(jnp.abs(theta))) or 1.0
        if max_resid <= tol * scale or restart == max_restarts - 1:
            break
        # thick restart: keep the k wanted Ritz vectors plus the next Krylov
        # direction A v_m orthogonalized against the whole basis (all Ritz
        # residuals are parallel to it in exact arithmetic); fall back to a
        # random draw if the Krylov space is exhausted.
        kept = y[:, keep]
        kept_av = av_basis[:, :m] @ s[:, keep]
        fresh = av_last
        for _ in range(2):
            fresh = fresh - v_basis @ (v_basis.T @ fresh)
        fnorm = jnp.linalg.norm(fresh)
        key, sub = jax.random.split(key)
        rand = jax.random.uniform(sub, (n,), dtype=dtype, minval=-1.0, maxval=1.0)
        rand = rand - kept @ (kept.T @ rand)
        rand = rand / jnp.maximum(jnp.linalg.norm(rand), 1e-30)
        fresh = jnp.where(fnorm > 1e-10, fresh / jnp.maximum(fnorm, 1e-30), rand)
        v_basis = jnp.zeros((n, m), dtype=dtype)
        v_basis = v_basis.at[:, :k].set(kept).at[:, k].set(fresh)
        av_basis = jnp.zeros((n, m), dtype=dtype).at[:, :k].set(kept_av)

    vals = theta[keep]
    vecs = y[:, keep]
    if which == "smallest":
        srt = jnp.argsort(vals)
    else:
        srt = jnp.argsort(-vals)
    return vals[srt], vecs[:, srt], n_iter


@takes_handle
def compute_smallest_eigenvectors(
    a: Operator,
    n: int,
    n_eig_vecs: int,
    maxiter: int = 4000,
    restart_iter: int = 0,
    tol: float = 1e-9,
    seed: int = 1234567,
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Smallest-eigenpair Lanczos (reference lanczos.hpp:754,1033).

    Returns ``(eigenvalues, eigenvectors, iters)`` — eigenvalues ascending,
    eigenvectors as columns.  ``restart_iter`` sets the Krylov subspace size
    (the reference's restart length); 0 picks ``max(4k, 32)``.
    """
    ncv = restart_iter if restart_iter > 0 else max(4 * n_eig_vecs, 32)
    ncv = min(ncv, n)
    max_restarts = max(1, maxiter // max(ncv, 1))
    vals, vecs, iters = _lanczos(a, n, n_eig_vecs, "smallest", ncv,
                                 max_restarts, tol, seed)
    # opt-in sanitizer (SURVEY §5; no-op unless enabled): a NaN/Inf in the
    # operator propagates into every Ritz value, so checking the output
    # catches seeded poison wherever it entered the iteration
    check_finite(vals, "lanczos eigenvalues")
    check_finite(vecs, "lanczos eigenvectors")
    return vals, vecs, iters


@takes_handle
def compute_largest_eigenvectors(
    a: Operator,
    n: int,
    n_eig_vecs: int,
    maxiter: int = 4000,
    restart_iter: int = 0,
    tol: float = 1e-9,
    seed: int = 1234567,
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Largest-eigenpair Lanczos (reference lanczos.hpp:1141); eigenvalues
    descending."""
    ncv = restart_iter if restart_iter > 0 else max(4 * n_eig_vecs, 32)
    ncv = min(ncv, n)
    max_restarts = max(1, maxiter // max(ncv, 1))
    vals, vecs, iters = _lanczos(a, n, n_eig_vecs, "largest", ncv,
                                 max_restarts, tol, seed)
    check_finite(vals, "lanczos eigenvalues")
    check_finite(vecs, "lanczos eigenvectors")
    return vals, vecs, iters
