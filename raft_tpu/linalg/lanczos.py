"""Restarted Lanczos eigensolver.

Reference: cpp/include/raft/linalg/lanczos.hpp (1,478 LoC) —
``computeSmallestEigenvectors`` (:754,1033) / ``computeLargestEigenvectors``
(:1141): Lanczos iteration (SpMV + dot/axpy/nrm2, :88-180), host LAPACK
``steqr`` on the tridiagonal, Francis-QR implicit restarts (:388,546).

TPU redesign: instead of translating the scalar-heavy CUDA iteration, we run
*thick-restart* Lanczos with **full reorthogonalization**: basis expansion is
a sequence of matvecs plus (n×m)ᵀ(n×1) projections — tall-skinny matmuls that
map straight onto the MXU — and the small (m×m) projected problem is solved
with a dense symmetric eigensolver (the ``steqr`` role).  Full
reorthogonalization costs a little more FLOP but removes the ghost-eigenvalue
pathology the reference's restart machinery exists to fight, and FLOPs are
what a TPU has.

The whole solve — basis expansion, Rayleigh-Ritz, thick restarts, and the
convergence test — is ONE jitted ``lax.while_loop`` computation: no
host↔device sync per restart and no per-call retrace (the r4 pathology:
the old per-restart Python loop re-traced its ``fori_loop`` closures every
call, so a 2k-vertex solve spent ~7.4 s compiling and ~0.05 s computing,
every time).  The operator crosses the jit boundary as a *pytree*
(``jax.tree_util.Partial``), so the executable is cached by (operator
structure, shapes) and reused across calls and instances.

The matrix is supplied as a callable ``mv(x) -> A @ x`` (the
``sparse_matrix_t::mv`` interface, reference spectral/matrix_wrappers.hpp:180)
or as a dense array.  For cache-friendliness a callable should be either a
bound method of a pytree-registered operator (``LaplacianMatrix.mv``) or a
``tree_util.Partial`` over array arguments; a plain closure still works but
embeds its captured arrays as compile-time constants (one recompile per new
operand — and the large-constant hazard on linked backends).
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple, Union

import jax
import jax.numpy as jnp
from jax.tree_util import Partial

from raft_tpu.core.debug import check_finite
from raft_tpu.core.utils import as_pytree_fn
from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle

Operator = Union[jnp.ndarray, Callable[[jnp.ndarray], jnp.ndarray]]


def _dense_mv(a: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    return a @ x


def _as_pytree_mv(a: Operator) -> Partial:
    """Normalize an operator to a pytree callable the jitted solver can
    take as an ARGUMENT (so its arrays are traced operands, not embedded
    constants, and the executable cache keys on structure + shapes).
    Dense arrays become matmul Partials; callables delegate to the
    shared :func:`raft_tpu.core.utils.as_pytree_fn` normalization."""
    if not callable(a):
        return Partial(_dense_mv, jnp.asarray(a))
    return as_pytree_fn(a)


def _operand_dtype(mv: Partial):
    for leaf in jax.tree_util.tree_leaves(mv):
        dt = getattr(leaf, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.floating):
            return dt
    return jnp.zeros(0).dtype


def _expand_basis(mv, v_basis: jnp.ndarray, av_basis: jnp.ndarray,
                  start: int, stop: int, key: jax.Array):
    """Grow an orthonormal basis from ``start`` to ``stop`` columns.

    v_basis is (n, m); columns [0, start) are already orthonormal and column
    ``start`` holds the (normalized) next direction.  av_basis caches
    ``mv(v_j)`` for every processed column so Rayleigh-Ritz never recomputes
    a matvec.  Each step: w = A v_j, orthogonalize against ALL previous
    columns twice (classical Gram-Schmidt, two passes — MXU-shaped), then
    normalize into column j+1.  If the Krylov space is exhausted (w ~ 0) the
    next column is re-seeded with a random direction orthogonal to the
    basis, keeping the basis orthonormal instead of fabricating zero
    columns (which would produce spurious zero-residual Ritz pairs).
    """
    n = v_basis.shape[0]

    def orthonormalize(w, vb):
        for _ in range(2):
            w = w - vb @ (vb.T @ w)
        return w, jnp.linalg.norm(w)

    def step(j, carry):
        vb, ab = carry
        v_j = jax.lax.dynamic_slice_in_dim(vb, j, 1, axis=1)[:, 0]
        av = mv(v_j)
        ab = jax.lax.dynamic_update_slice_in_dim(ab, av[:, None], j, axis=1)
        w, nrm = orthonormalize(av, vb)

        def krylov_next(_):
            return w / jnp.where(nrm > 0, nrm, 1.0)

        def reseed(_):
            r = jax.random.uniform(
                jax.random.fold_in(key, j), (n,), dtype=vb.dtype,
                minval=-1.0, maxval=1.0)
            r, rn = orthonormalize(r, vb)
            return r / jnp.maximum(rn, 1e-30)

        w = jax.lax.cond(nrm > 1e-10, krylov_next, reseed, operand=None)
        vb = jax.lax.dynamic_update_slice_in_dim(vb, w[:, None], j + 1, axis=1)
        return vb, ab

    return jax.lax.fori_loop(start, stop, step, (v_basis, av_basis))


def _ritz(v_basis: jnp.ndarray, av_basis: jnp.ndarray):
    """Rayleigh-Ritz on the cached basis/A-basis pair."""
    h = v_basis.T @ av_basis
    h = 0.5 * (h + h.T)
    theta, s = jnp.linalg.eigh(h)
    y = v_basis @ s
    # residual norms ||A y - theta y|| per Ritz pair
    r = av_basis @ s - y * theta[None, :]
    resid = jnp.linalg.norm(r, axis=0)
    return theta, y, s, resid


def _keep_order(theta: jnp.ndarray, which: str) -> jnp.ndarray:
    return jnp.argsort(theta if which == "smallest" else -theta)


def _converged(theta, resid, keep, tol):
    max_resid = jnp.max(resid[keep])
    scale = jnp.max(jnp.abs(theta))
    scale = jnp.where(scale > 0, scale, 1.0)
    return max_resid <= tol * scale


@functools.partial(
    jax.jit,
    static_argnames=("n", "k", "which", "m", "max_restarts"))
def _lanczos_run(mv, n, k, which, m, max_restarts, tol, seed):
    # tol and seed are traced OPERANDS, not static: a caller sweeping
    # tolerances or deriving per-call seeds must hit the executable
    # cache, not recompile the whole solver per value
    """The whole thick-restart solve as one compiled computation.

    Krylov orthogonality is what convergence rests on: every matmul in
    the solver (projections, re-orthogonalization, Ritz rotation, and a
    matrix-operand mv) must run f32-faithful.  XLA's TPU default for f32
    matmuls is single-pass bf16 — enough orthogonality loss to stall
    restarts — so the whole body is pinned to "highest".
    """
    with jax.default_matmul_precision("highest"):
        dtype = _operand_dtype(mv)
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        v0 = jax.random.uniform(sub, (n,), dtype=dtype,
                                minval=-1.0, maxval=1.0)
        v0 = v0 / jnp.linalg.norm(v0)

        def expand(vb, ab, start, sub):
            vb, ab = _expand_basis(mv, vb, ab, start, m - 1, sub)
            av_last = mv(vb[:, m - 1])
            ab = ab.at[:, m - 1].set(av_last)
            return vb, ab, av_last

        v_basis = jnp.zeros((n, m), dtype=dtype).at[:, 0].set(v0)
        av_basis = jnp.zeros((n, m), dtype=dtype)
        key, sub = jax.random.split(key)
        v_basis, av_basis, av_last = expand(v_basis, av_basis, 0, sub)
        theta, y, s, resid = _ritz(v_basis, av_basis)
        carry0 = (v_basis, av_basis, av_last, theta, y, s, resid, key,
                  jnp.int32(0), jnp.int32(m))

        def cond(carry):
            _, _, _, theta, _, _, resid, _, restart, _ = carry
            keep = _keep_order(theta, which)[:k]
            return jnp.logical_and(restart < max_restarts - 1,
                                   ~_converged(theta, resid, keep, tol))

        def body(carry):
            (vb, ab, av_last, theta, y, s, resid, key, restart,
             n_iter) = carry
            keep = _keep_order(theta, which)[:k]
            # thick restart: keep the k wanted Ritz vectors plus the
            # next Krylov direction A v_m orthogonalized against the
            # whole basis (all Ritz residuals are parallel to it in
            # exact arithmetic); fall back to a random draw if the
            # Krylov space is exhausted.
            kept = y[:, keep]
            kept_av = ab @ s[:, keep]
            fresh = av_last
            for _ in range(2):
                fresh = fresh - vb @ (vb.T @ fresh)
            fnorm = jnp.linalg.norm(fresh)
            key, sub = jax.random.split(key)
            rand = jax.random.uniform(sub, (n,), dtype=vb.dtype,
                                      minval=-1.0, maxval=1.0)
            rand = rand - kept @ (kept.T @ rand)
            rand = rand / jnp.maximum(jnp.linalg.norm(rand), 1e-30)
            fresh = jnp.where(fnorm > 1e-10,
                              fresh / jnp.maximum(fnorm, 1e-30), rand)
            vb = jnp.zeros_like(vb).at[:, :k].set(kept).at[:, k].set(fresh)
            ab = jnp.zeros_like(ab).at[:, :k].set(kept_av)
            key, sub = jax.random.split(key)
            vb, ab, av_last = expand(vb, ab, k, sub)
            theta, y, s, resid = _ritz(vb, ab)
            return (vb, ab, av_last, theta, y, s, resid, key,
                    restart + 1, n_iter + jnp.int32(m - k))

        (_, _, _, theta, y, _, _, _, _, n_iter) = jax.lax.while_loop(
            cond, body, carry0)

        keep = _keep_order(theta, which)[:k]
        vals = theta[keep]
        vecs = y[:, keep]
        srt = _keep_order(vals, "smallest" if which == "smallest"
                          else "largest")
        return vals[srt], vecs[:, srt], n_iter


def _lanczos(
    a: Operator,
    n: int,
    k: int,
    which: str,
    ncv: int,
    max_restarts: int,
    tol: float,
    seed: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    expects(0 < k < n, "lanczos: need 0 < k < n (k=%d, n=%d)", k, n)
    m = min(max(ncv, 2 * k + 1), n)
    if m >= n:
        # the basis spans the whole space after one expansion, so
        # Rayleigh-Ritz is already the exact (f32) eigendecomposition;
        # further restarts only churn floating-point noise through the
        # wanted vectors (an unreachable tol would otherwise spin every
        # small-n solve through max_restarts of that churn)
        max_restarts = 1
    mv = _as_pytree_mv(a)
    vals, vecs, n_iter = _lanczos_run(mv, n, k, which, m, max_restarts,
                                      jnp.float32(tol),
                                      jnp.int32(seed))
    return vals, vecs, int(n_iter)


@takes_handle
def compute_smallest_eigenvectors(
    a: Operator,
    n: int,
    n_eig_vecs: int,
    maxiter: int = 4000,
    restart_iter: int = 0,
    tol: float = 1e-9,
    seed: int = 1234567,
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Smallest-eigenpair Lanczos (reference lanczos.hpp:754,1033).

    Returns ``(eigenvalues, eigenvectors, iters)`` — eigenvalues ascending,
    eigenvectors as columns.  ``restart_iter`` sets the Krylov subspace size
    (the reference's restart length); 0 picks ``max(4k, 32)``.
    """
    ncv = restart_iter if restart_iter > 0 else max(4 * n_eig_vecs, 32)
    ncv = min(ncv, n)
    max_restarts = max(1, maxiter // max(ncv, 1))
    vals, vecs, iters = _lanczos(a, n, n_eig_vecs, "smallest", ncv,
                                 max_restarts, tol, seed)
    # opt-in sanitizer (SURVEY §5; no-op unless enabled): a NaN/Inf in the
    # operator propagates into every Ritz value, so checking the output
    # catches seeded poison wherever it entered the iteration
    check_finite(vals, "lanczos eigenvalues")
    check_finite(vecs, "lanczos eigenvectors")
    return vals, vecs, iters


@takes_handle
def compute_largest_eigenvectors(
    a: Operator,
    n: int,
    n_eig_vecs: int,
    maxiter: int = 4000,
    restart_iter: int = 0,
    tol: float = 1e-9,
    seed: int = 1234567,
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Largest-eigenpair Lanczos (reference lanczos.hpp:1141); eigenvalues
    descending."""
    ncv = restart_iter if restart_iter > 0 else max(4 * n_eig_vecs, 32)
    ncv = min(ncv, n)
    max_restarts = max(1, maxiter // max(ncv, 1))
    vals, vecs, iters = _lanczos(a, n, n_eig_vecs, "largest", ncv,
                                 max_restarts, tol, seed)
    check_finite(vals, "lanczos eigenvalues")
    check_finite(vecs, "lanczos eigenvectors")
    return vals, vecs, iters
