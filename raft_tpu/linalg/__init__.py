"""Dense linear algebra primitives.

TPU-native equivalent of the reference's ``raft::linalg`` module
(cpp/include/raft/linalg/).  Where the reference hand-wraps cuBLAS/cuSOLVER
and writes custom CUDA kernels, we lower to XLA HLO: matmuls hit the MXU,
elementwise ops and reductions fuse, and solvers use XLA's native
eigendecomposition/SVD/QR.  The one genuinely iterative solver — Lanczos —
is built from our own primitives with the tridiagonal stage on the host,
mirroring the reference's structure (linalg/lanczos.hpp).
"""

from raft_tpu.linalg.gemm import gemm, gemv
from raft_tpu.linalg.eig import eig_dc, eig_jacobi, eig_sel_dc
from raft_tpu.linalg.svd import svd_eig, svd_jacobi, svd_qr, svd_reconstruction
from raft_tpu.linalg.qr import qr_get_q, qr_get_qr
from raft_tpu.linalg.cholesky import cholesky_rank1_update
from raft_tpu.linalg.elementwise import (
    add,
    add_scalar,
    binary_op,
    divide_scalar,
    eltwise_add,
    eltwise_divide,
    eltwise_multiply,
    eltwise_sub,
    map_op,
    multiply_scalar,
    subtract,
    subtract_scalar,
    unary_op,
)
from raft_tpu.linalg.reduce import (
    coalesced_reduction,
    map_then_reduce,
    map_then_sum_reduce,
    reduce,
    strided_reduction,
)
from raft_tpu.linalg.norm import (
    L1Norm,
    L2Norm,
    LinfNorm,
    NormType,
    col_norm,
    mean_squared_error,
    row_norm,
)
from raft_tpu.linalg.matrix_vector_op import matrix_vector_op
from raft_tpu.linalg.transpose import transpose
from raft_tpu.linalg.init import range_init
from raft_tpu.linalg.lanczos import (
    compute_largest_eigenvectors,
    compute_smallest_eigenvectors,
)

__all__ = [
    "gemm",
    "gemv",
    "eig_dc",
    "eig_sel_dc",
    "eig_jacobi",
    "svd_qr",
    "svd_eig",
    "svd_jacobi",
    "svd_reconstruction",
    "qr_get_q",
    "qr_get_qr",
    "cholesky_rank1_update",
    "unary_op",
    "binary_op",
    "map_op",
    "eltwise_add",
    "eltwise_sub",
    "eltwise_multiply",
    "eltwise_divide",
    "add",
    "subtract",
    "add_scalar",
    "subtract_scalar",
    "multiply_scalar",
    "divide_scalar",
    "reduce",
    "coalesced_reduction",
    "strided_reduction",
    "map_then_reduce",
    "map_then_sum_reduce",
    "NormType",
    "L1Norm",
    "L2Norm",
    "LinfNorm",
    "row_norm",
    "col_norm",
    "mean_squared_error",
    "matrix_vector_op",
    "transpose",
    "range_init",
    "compute_smallest_eigenvectors",
    "compute_largest_eigenvectors",
]
