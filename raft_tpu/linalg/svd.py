"""Singular value decomposition family.

Reference: cpp/include/raft/linalg/svd.cuh — ``svdQR`` (:55), ``svdEig``
(SVD via eigendecomposition of AᵀA, :136), ``svdJacobi`` (:213),
``svdReconstruction`` (:296), plus ``evaluateSVDByL2Norm`` reconstruction
check.  ``svd_eig`` keeps the real AᵀA algorithm (it is genuinely faster for
tall-skinny matrices and exercises the MXU); the QR/Jacobi variants lower to
XLA's SVD.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


@takes_handle
def svd_qr(
    a: jnp.ndarray, gen_u: bool = True, gen_v: bool = True
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Thin SVD ``a = u @ diag(s) @ v.T`` (reference svd.cuh:55 ``svdQR``).

    Returns ``(u, s, v)`` with ``v`` as a matrix of right singular vectors
    in columns (not vᵀ), matching the reference's output layout.
    """
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return (u if gen_u else None), s, (vt.T if gen_v else None)


@takes_handle
def svd_eig(a: jnp.ndarray, gen_left_vec: bool = True
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """SVD via symmetric eigendecomposition of AᵀA (reference svd.cuh:136).

    For an (m, n) matrix with m >= n this does one (n, n) eigensolve plus a
    single MXU matmul to recover U — the same trick the reference uses to
    avoid the expensive QR-iteration SVD.  Singular values descend.
    """
    m, n = a.shape
    expects(m >= n, "svd_eig: requires m >= n (got %d x %d)", m, n)
    ata = a.T @ a
    w, v = jnp.linalg.eigh(ata)
    # ascending eigenvalues -> descending singular values
    w = w[::-1]
    v = v[:, ::-1]
    s = jnp.sqrt(jnp.clip(w, 0.0, None))
    u = None
    if gen_left_vec:
        u = (a @ v) / jnp.where(s > 0, s, 1.0)[None, :]
    return u, s, v


@takes_handle
def svd_jacobi(
    a: jnp.ndarray,
    gen_u: bool = True,
    gen_v: bool = True,
    tol: float = 1e-7,
    sweeps: int = 15,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Jacobi-SVD signature parity (reference svd.cuh:213 ``svdJacobi``)."""
    del tol, sweeps
    return svd_qr(a, gen_u=gen_u, gen_v=gen_v)


@takes_handle
def svd_reconstruction(u: jnp.ndarray, s: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Rebuild ``u @ diag(s) @ v.T`` (reference svd.cuh:296)."""
    return (u * s[None, :]) @ v.T


@takes_handle
def evaluate_svd_by_l2_norm(
    a: jnp.ndarray, u: jnp.ndarray, s: jnp.ndarray, v: jnp.ndarray, tol: float
) -> bool:
    """Relative Frobenius reconstruction error check (reference svd.cuh:329)."""
    recon = svd_reconstruction(u, s, v)
    err = jnp.linalg.norm(a - recon) / jnp.maximum(jnp.linalg.norm(a), 1e-30)
    return bool(err < tol)
