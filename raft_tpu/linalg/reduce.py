"""Reductions with pluggable main/reduce/final lambdas.

Reference: cpp/include/raft/linalg/ — ``coalescedReduction``
(coalesced_reduction.cuh:97, reduce along the contiguous dimension),
``stridedReduction`` (strided_reduction.cuh:138, reduce along the strided
dimension), the generic row/col ``reduce`` dispatcher (reduce.cuh:61),
``mapThenReduce``/``mapThenSumReduce`` (map_then_reduce.cuh:113,144).

On TPU the distinction between coalesced and strided disappears — XLA picks
the layout — but the lambda-parameterised semantics (main_op applied per
element with its index, reduce_op to combine, final_op on the result) are
preserved exactly, since consumers build norms/statistics out of them.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


def _identity_main(x, idx):
    return x


def _apply_reduce(mapped: jnp.ndarray, axis: int, reduce_op, init):
    if reduce_op is None:
        return jnp.sum(mapped, axis=axis)
    # generic lambda reduction: associative scan via jnp reduce primitives
    # for the common cases, else a fold
    import jax

    def fold(carry, x):
        return reduce_op(carry, x), None

    moved = jnp.moveaxis(mapped, axis, 0)
    carry0 = jnp.full(moved.shape[1:], init, dtype=moved.dtype)
    out, _ = jax.lax.scan(fold, carry0, moved)
    return out


@takes_handle
def coalesced_reduction(
    data: jnp.ndarray,
    main_op: Optional[Callable] = None,
    reduce_op: Optional[Callable] = None,
    final_op: Optional[Callable] = None,
    init: float = 0.0,
    inplace_accumulate: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Reduce along the last (contiguous) axis (reference
    coalesced_reduction.cuh:97).  ``main_op(value, index)`` maps each
    element; ``reduce_op`` combines; ``final_op`` transforms the result."""
    main_op = main_op or _identity_main
    idx = jnp.arange(data.shape[-1])
    mapped = main_op(data, idx)
    out = _apply_reduce(mapped, -1, reduce_op, init)
    if inplace_accumulate is not None:
        out = out + inplace_accumulate
    if final_op is not None:
        out = final_op(out)
    return out


@takes_handle
def strided_reduction(
    data: jnp.ndarray,
    main_op: Optional[Callable] = None,
    reduce_op: Optional[Callable] = None,
    final_op: Optional[Callable] = None,
    init: float = 0.0,
    inplace_accumulate: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Reduce along the first (strided) axis (reference
    strided_reduction.cuh:138)."""
    main_op = main_op or _identity_main
    idx = jnp.arange(data.shape[0])[:, None]
    mapped = main_op(data, idx)
    out = _apply_reduce(mapped, 0, reduce_op, init)
    if inplace_accumulate is not None:
        out = out + inplace_accumulate
    if final_op is not None:
        out = final_op(out)
    return out


@takes_handle
def reduce(
    data: jnp.ndarray,
    along_rows: bool = True,
    row_major: bool = True,
    main_op: Optional[Callable] = None,
    reduce_op: Optional[Callable] = None,
    final_op: Optional[Callable] = None,
    init: float = 0.0,
) -> jnp.ndarray:
    """Generic row/column reduction dispatcher (reference reduce.cuh:61).

    ``along_rows=True`` reduces each row to a scalar (output length =
    n_rows).  The reference's rowMajor flag selects coalesced vs strided
    kernels for the same logical reduction (reduce.cuh:74-82); with JAX
    arrays the logical view is all that matters, so ``row_major`` is
    accepted for parity but does not change semantics.
    """
    del row_major
    fn = coalesced_reduction if along_rows else strided_reduction
    return fn(data, main_op=main_op, reduce_op=reduce_op, final_op=final_op, init=init)


@takes_handle
def map_then_reduce(
    op: Callable,
    reduce_op: Optional[Callable],
    init: float,
    *arrays: jnp.ndarray,
) -> jnp.ndarray:
    """Map an n-ary lambda then reduce to a scalar (reference
    map_then_reduce.cuh:113)."""
    mapped = op(*arrays)
    if reduce_op is None:
        return jnp.sum(mapped)
    flat = mapped.ravel()
    return _apply_reduce(flat, 0, reduce_op, init)


@takes_handle
def map_then_sum_reduce(op: Callable, *arrays: jnp.ndarray) -> jnp.ndarray:
    """Map then sum-reduce (reference map_then_reduce.cuh:144)."""
    return jnp.sum(op(*arrays))
