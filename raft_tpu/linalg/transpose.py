"""Transpose (reference cpp/include/raft/linalg/transpose.h:36,73 — cuBLAS
geam out-of-place and a square in-place variant).  One XLA op here."""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


@takes_handle
def transpose(a: jnp.ndarray) -> jnp.ndarray:
    """Out-of-place transpose (reference transpose.h:36)."""
    return a.T
