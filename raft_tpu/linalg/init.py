"""Sequence initialization (reference cpp/include/raft/linalg/init.h:40
``range(out, start, end, stream)`` — fill with [start, end))."""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


@takes_handle
def range_init(start: int, end: int, dtype=jnp.int32) -> jnp.ndarray:
    """Fill with the integer range [start, end) (reference init.h:40)."""
    return jnp.arange(start, end, dtype=dtype)
