"""QR decomposition (reference cpp/include/raft/linalg/qr.cuh:44,88 —
cuSOLVER geqrf/orgqr).  XLA's QR is a single fused op on TPU."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


@takes_handle
def qr_get_q(a: jnp.ndarray) -> jnp.ndarray:
    """Orthonormal Q of the thin QR (reference qr.cuh:44 ``qrGetQ``)."""
    q, _ = jnp.linalg.qr(a, mode="reduced")
    return q


@takes_handle
def qr_get_qr(a: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Thin QR ``(q, r)`` (reference qr.cuh:88 ``qrGetQR``)."""
    return jnp.linalg.qr(a, mode="reduced")
