"""Broadcast a vector op across matrix rows or columns.

Reference: cpp/include/raft/linalg/matrix_vector_op.cuh:120
(``matrixVectorOp``): apply ``op(mat_element, vec_element)`` where the
vector is broadcast along rows or columns; a two-vector variant (:190)
takes ``op(mat, vec1, vec2)``.  XLA broadcasting does the indexing; the
named entry point keeps consumer code (mean_center, normalize, whiten)
readable.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


@takes_handle
def matrix_vector_op(
    mat: jnp.ndarray,
    vec: jnp.ndarray,
    op: Callable,
    bcast_along_rows: bool = True,
    vec2: Optional[jnp.ndarray] = None,
    row_major: bool = True,
) -> jnp.ndarray:
    """Apply ``op`` between ``mat`` and broadcast ``vec`` (and optionally
    ``vec2``).

    ``bcast_along_rows=True`` means the vector has one entry per *column*
    and is broadcast down the rows (the reference's ``bcastAlongRows``,
    matrix_vector_op.cuh:105 docs); False means one entry per row.
    ``row_major`` kept for signature parity (layout is XLA's concern).
    """
    del row_major
    n = mat.shape[-1] if bcast_along_rows else mat.shape[0]
    expects(
        vec.shape[0] == n,
        "matrix_vector_op: vector length %d does not match matrix dim %d",
        vec.shape[0],
        n,
    )
    v = vec[None, :] if bcast_along_rows else vec[:, None]
    if vec2 is None:
        return op(mat, v)
    v2 = vec2[None, :] if bcast_along_rows else vec2[:, None]
    return op(mat, v, v2)
