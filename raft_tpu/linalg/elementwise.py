"""Elementwise operations.

Reference: cpp/include/raft/linalg/ — ``unaryOp``/``writeOnlyUnaryOp``
(unary_op.cuh:73,96), ``binaryOp`` (binary_op.cuh:84), ``eltwiseAdd/Sub/
Mul/Div`` (eltwise.cuh:37-114), scalar variants (add.cuh:40-87,
subtract.cuh:41-90, multiply.cuh, divide.cuh), generic ``map`` over n
arrays (map.cuh:65).  The reference hand-vectorizes these with TxN_t loads
(vectorized.cuh); XLA fuses and vectorizes elementwise lambdas
automatically, so each is a one-liner — kept as named functions so consumer
code keeps its vocabulary.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


@takes_handle
def unary_op(x: jnp.ndarray, op: Callable) -> jnp.ndarray:
    """Apply ``op`` elementwise (reference unary_op.cuh:73)."""
    return op(x)


@takes_handle
def write_only_unary_op(shape, dtype, op: Callable) -> jnp.ndarray:
    """Generate an array from flat indices (reference unary_op.cuh:96:
    the lambda receives the output offset)."""
    idx = jnp.arange(int(jnp.prod(jnp.array(shape))))
    return op(idx).astype(dtype).reshape(shape)


@takes_handle
def binary_op(x: jnp.ndarray, y: jnp.ndarray, op: Callable) -> jnp.ndarray:
    """Apply a binary lambda elementwise (reference binary_op.cuh:84)."""
    return op(x, y)


@takes_handle
def map_op(op: Callable, *arrays: jnp.ndarray) -> jnp.ndarray:
    """Map an n-ary lambda over n same-shaped arrays (reference map.cuh:65)."""
    return op(*arrays)


@takes_handle
def eltwise_add(x, y):
    """(reference eltwise.cuh:37)"""
    return x + y


@takes_handle
def eltwise_sub(x, y):
    """(reference eltwise.cuh:63)"""
    return x - y


@takes_handle
def eltwise_multiply(x, y):
    """(reference eltwise.cuh:76)"""
    return x * y


@takes_handle
def eltwise_divide(x, y):
    """(reference eltwise.cuh:89)"""
    return x / y


@takes_handle
def eltwise_divide_check_zero(x, y):
    """Divide with 0 where divisor is 0 (reference eltwise.cuh:102)."""
    return jnp.where(y == 0, 0, x / jnp.where(y == 0, 1, y))


@takes_handle
def add(x, y):
    """(reference add.cuh:58 ``add``)"""
    return x + y


@takes_handle
def subtract(x, y):
    """(reference subtract.cuh:58)"""
    return x - y


@takes_handle
def add_scalar(x, scalar):
    """(reference add.cuh:40 ``addScalar``)"""
    return x + scalar


@takes_handle
def subtract_scalar(x, scalar):
    """(reference subtract.cuh:41 ``subtractScalar``)"""
    return x - scalar


@takes_handle
def multiply_scalar(x, scalar):
    """(reference multiply.cuh:38 ``multiplyScalar``)"""
    return x * scalar


@takes_handle
def divide_scalar(x, scalar):
    """(reference divide.cuh:38 ``divideScalar``)"""
    return x / scalar
