"""Row/column norms and MSE.

Reference: cpp/include/raft/linalg/norm.cuh — ``NormType {L1Norm, L2Norm}``
(:25), ``rowNorm`` (:48) / ``colNorm`` (:105) with optional sqrt and a
``fin_op`` epilogue; mean_squared_error.cuh:36.  We add ``LinfNorm`` (used
by some consumers via the generic reduce path in the reference).
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.handle import takes_handle


class NormType(enum.IntEnum):
    """(reference norm.cuh:25)"""

    L1Norm = 0
    L2Norm = 1
    LinfNorm = 2


L1Norm = NormType.L1Norm
L2Norm = NormType.L2Norm
LinfNorm = NormType.LinfNorm


def _norm(data: jnp.ndarray, axis: int, norm_type: NormType, do_sqrt: bool,
          fin_op: Optional[Callable]) -> jnp.ndarray:
    if norm_type == NormType.L1Norm:
        out = jnp.sum(jnp.abs(data), axis=axis)
    elif norm_type == NormType.L2Norm:
        out = jnp.sum(data * data, axis=axis)
    else:
        out = jnp.max(jnp.abs(data), axis=axis)
    if do_sqrt:
        out = jnp.sqrt(out)
    if fin_op is not None:
        out = fin_op(out)
    return out


@takes_handle
def row_norm(
    data: jnp.ndarray,
    norm_type: NormType = NormType.L2Norm,
    do_sqrt: bool = False,
    fin_op: Optional[Callable] = None,
) -> jnp.ndarray:
    """Per-row norm (reference norm.cuh:48 ``rowNorm``).  L2 without sqrt
    returns squared norms, the reference default used by expanded
    distances."""
    return _norm(data, -1, norm_type, do_sqrt, fin_op)


@takes_handle
def col_norm(
    data: jnp.ndarray,
    norm_type: NormType = NormType.L2Norm,
    do_sqrt: bool = False,
    fin_op: Optional[Callable] = None,
) -> jnp.ndarray:
    """Per-column norm (reference norm.cuh:105 ``colNorm``)."""
    return _norm(data, 0, norm_type, do_sqrt, fin_op)


@takes_handle
def mean_squared_error(a: jnp.ndarray, b: jnp.ndarray, weight: float = 1.0) -> jnp.ndarray:
    """``weight * mean((a-b)^2)`` (reference mean_squared_error.cuh:36)."""
    diff = a - b
    return weight * jnp.mean(diff * diff)
