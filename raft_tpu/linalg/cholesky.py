"""Rank-1 Cholesky update.

Reference: cpp/include/raft/linalg/cholesky_r1_update.cuh:125 — given the
Cholesky factor of the leading (n-1, n-1) block of A, extend it to the
(n, n) block after a new row/column is appended.  The reference builds this
from a triangular solve + dot product; we do the same with XLA's
``solve_triangular`` so the incremental-SVM/kernel use case carries over.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


def _checked_sqrt(d: jnp.ndarray, eps: float | None) -> jnp.ndarray:
    """sqrt of the new diagonal element with the reference's
    positive-definiteness check (cholesky_r1_update.cuh docs: raises when
    d <= eps).  Eager callers get a LogicError; under jit (where raising on
    a traced value is impossible) the failure surfaces as NaN, which
    ``jnp.sqrt`` of a negative produces anyway."""
    if eps is not None:
        try:
            ok = bool(d > eps)
        except Exception:  # traced value: signal via NaN instead of raising
            return jnp.sqrt(jnp.where(d > eps, d, jnp.nan))
        expects(ok, "cholesky_rank1_update: matrix is not positive definite")
    return jnp.sqrt(d)


@takes_handle
def cholesky_rank1_update(
    l_full: jnp.ndarray, n: int, lower: bool = True, eps: float | None = None
) -> jnp.ndarray:
    """Extend a Cholesky factorization by one row/column.

    Parameters mirror the reference (cholesky_r1_update.cuh:125): ``l_full``
    is an (n, n) array whose leading (n-1, n-1) block already holds the
    factor L of A[:n-1, :n-1] and whose last row (lower) or column (upper)
    holds the new entries of A.  Returns the array with the new row/column
    replaced by the updated factor.  ``eps``: positive-definiteness
    threshold for the new diagonal element (see :func:`_checked_sqrt`).
    """
    expects(l_full.ndim == 2 and l_full.shape[0] == l_full.shape[1],
            "cholesky_rank1_update: square input required")
    expects(1 <= n <= l_full.shape[0], "cholesky_rank1_update: invalid n=%d", n)
    if n == 1:
        return l_full.at[0, 0].set(_checked_sqrt(l_full[0, 0], eps))
    k = n - 1
    if lower:
        a_col = l_full[k, :k]  # new row of A (== column by symmetry)
        l_sub = l_full[:k, :k]
        # L_21 = L^-1 a  (triangular solve), L_22 = sqrt(a_nn - ||L_21||^2)
        l21 = jsl.solve_triangular(l_sub, a_col, lower=True)
        l22 = _checked_sqrt(l_full[k, k] - jnp.dot(l21, l21), eps)
        out = l_full.at[k, :k].set(l21)
        return out.at[k, k].set(l22)
    else:
        a_row = l_full[:k, k]
        u_sub = l_full[:k, :k]
        u12 = jsl.solve_triangular(u_sub.T, a_row, lower=True)
        u22 = _checked_sqrt(l_full[k, k] - jnp.dot(u12, u12), eps)
        out = l_full.at[:k, k].set(u12)
        return out.at[k, k].set(u22)
