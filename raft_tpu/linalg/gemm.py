"""GEMM / GEMV.

Reference: cpp/include/raft/linalg/gemm.cuh:46,73,111 (cuBLAS-backed, three
overloads with alpha/beta and transpose flags) and gemv.h:29-164.  On TPU a
matmul is a single MXU-shaped XLA op; alpha/beta epilogues fuse into it, so
the whole overload family collapses to two functions.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


@takes_handle
def gemm(
    a: jnp.ndarray,
    b: jnp.ndarray,
    trans_a: bool = False,
    trans_b: bool = False,
    alpha: float = 1.0,
    beta: float = 0.0,
    c: Optional[jnp.ndarray] = None,
    preferred_element_type=None,
) -> jnp.ndarray:
    """``alpha * op(a) @ op(b) + beta * c`` (reference gemm.cuh:73).

    ``preferred_element_type`` controls MXU accumulation dtype (e.g. keep
    float32 accumulation for bfloat16 inputs).
    """
    opa = a.T if trans_a else a
    opb = b.T if trans_b else b
    expects(
        opa.shape[-1] == opb.shape[-2 if opb.ndim > 1 else 0],
        "gemm: inner dimensions mismatch (%d vs %d)",
        opa.shape[-1],
        opb.shape[-2 if opb.ndim > 1 else 0],
    )
    out = jnp.matmul(opa, opb, preferred_element_type=preferred_element_type)
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(c is not None, "gemm: beta != 0 requires c")
        out = out + beta * c
    return out


@takes_handle
def gemv(
    a: jnp.ndarray,
    x: jnp.ndarray,
    trans_a: bool = False,
    alpha: float = 1.0,
    beta: float = 0.0,
    y: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``alpha * op(a) @ x + beta * y`` (reference gemv.h:29-164)."""
    opa = a.T if trans_a else a
    expects(
        opa.shape[-1] == x.shape[0],
        "gemv: dimension mismatch (%d vs %d)",
        opa.shape[-1],
        x.shape[0],
    )
    out = opa @ x
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(y is not None, "gemv: beta != 0 requires y")
        out = out + beta * y
    return out
