"""GEMM / GEMV.

Reference: cpp/include/raft/linalg/gemm.cuh:46,73,111 (cuBLAS-backed, three
overloads with alpha/beta and transpose flags) and gemv.h:29-164.  On TPU a
matmul is a single MXU-shaped XLA op; alpha/beta epilogues fuse into it, so
the whole overload family collapses to two functions.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


@takes_handle
def gemm(
    a: jnp.ndarray,
    b: jnp.ndarray,
    trans_a: bool = False,
    trans_b: bool = False,
    alpha: float = 1.0,
    beta: float = 0.0,
    c: Optional[jnp.ndarray] = None,
    preferred_element_type=None,
    precision: str = "highest",
) -> jnp.ndarray:
    """``alpha * op(a) @ op(b) + beta * c`` (reference gemm.cuh:73).

    ``preferred_element_type`` controls MXU accumulation dtype (e.g. keep
    float32 accumulation for bfloat16 inputs).  ``precision`` is the MXU
    pass mode: ``"highest"`` (default) keeps f32-faithful math, matching
    cuBLAS SGEMM's default contract — on TPU the XLA *default* for f32
    operands is single-pass bf16 (the TF32-math-mode analog, which
    cuBLAS requires an explicit opt-IN for), so faithfulness must be the
    default and speed the opt-out (``precision="default"`` ≈ 2-3x
    faster; the bench's linalg rung reports both).
    """
    opa = a.T if trans_a else a
    opb = b.T if trans_b else b
    expects(
        opa.shape[-1] == opb.shape[-2 if opb.ndim > 1 else 0],
        "gemm: inner dimensions mismatch (%d vs %d)",
        opa.shape[-1],
        opb.shape[-2 if opb.ndim > 1 else 0],
    )
    out = jnp.matmul(opa, opb, preferred_element_type=preferred_element_type,
                     precision=precision)
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(c is not None, "gemm: beta != 0 requires c")
        out = out + beta * c
    return out


@takes_handle
def gemv(
    a: jnp.ndarray,
    x: jnp.ndarray,
    trans_a: bool = False,
    alpha: float = 1.0,
    beta: float = 0.0,
    y: Optional[jnp.ndarray] = None,
    precision: str = "highest",
) -> jnp.ndarray:
    """``alpha * op(a) @ x + beta * y`` (reference gemv.h:29-164).
    ``precision``: see :func:`gemm` (same faithful-by-default rule)."""
    opa = a.T if trans_a else a
    expects(
        opa.shape[-1] == x.shape[0],
        "gemv: dimension mismatch (%d vs %d)",
        opa.shape[-1],
        x.shape[0],
    )
    out = jnp.matmul(opa, x, precision=precision)
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(y is not None, "gemv: beta != 0 requires y")
        out = out + beta * y
    return out
