"""Symmetric eigendecomposition.

Reference: cpp/include/raft/linalg/eig.cuh — ``eigDC`` (cuSOLVER syevd, :90),
``eigSelDC`` (syevdx selecting the top/bottom subset, :169), ``eigJacobi``
(Jacobi sweeps with tolerance, :276).  XLA provides a fused symmetric
eigensolver; the Jacobi variant keeps its (tol, sweeps) signature for parity
but lowers to the same op — on TPU there is no reason to run a slower
hand-rolled Jacobi when the compiler's solver exists.

All variants return eigenvalues in ascending order with matching
eigenvectors, the reference's cuSOLVER convention.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects

from raft_tpu.core.handle import takes_handle


def _check_square(a: jnp.ndarray, name: str) -> None:
    expects(a.ndim == 2 and a.shape[0] == a.shape[1], "%s: matrix must be square", name)


@takes_handle
def eig_dc(a: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full symmetric eigendecomposition (reference eig.cuh:90 ``eigDC``).

    Returns ``(eig_vectors, eig_vals)`` with eigenvalues ascending;
    ``eig_vectors[:, i]`` is the i-th eigenvector.
    """
    _check_square(a, "eig_dc")
    w, v = jnp.linalg.eigh(a)
    return v, w


@takes_handle
def eig_sel_dc(
    a: jnp.ndarray, n_eig_vals: int, largest: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Select ``n_eig_vals`` extreme eigenpairs (reference eig.cuh:169
    ``eigSelDC``; the reference selects via syevdx ranges).

    ``largest=False`` returns the smallest (ascending), matching the
    reference default used by spectral methods.
    """
    _check_square(a, "eig_sel_dc")
    expects(
        0 < n_eig_vals <= a.shape[0],
        "eig_sel_dc: n_eig_vals must be in (0, %d], got %d",
        a.shape[0],
        n_eig_vals,
    )
    w, v = jnp.linalg.eigh(a)
    if largest:
        return v[:, -n_eig_vals:], w[-n_eig_vals:]
    return v[:, :n_eig_vals], w[:n_eig_vals]


@takes_handle
def eig_jacobi(
    a: jnp.ndarray, tol: float = 1e-7, sweeps: int = 15
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Jacobi-method signature parity (reference eig.cuh:276 ``eigJacobi``).

    ``tol``/``sweeps`` are accepted for API compatibility; XLA's fused
    eigensolver meets or exceeds Jacobi accuracy.
    """
    del tol, sweeps
    return eig_dc(a)
