"""Cluster session orchestration: the Dask/`Comms` lifecycle, TPU-native.

Reference: python/raft/dask/common/comms.py — the ``Comms`` session object
(:37) generates an NCCL unique id (:136-169), runs ``_func_init_all`` on
every Dask worker (:414-460) to init NCCL/UCX and
``inject_comms_on_handle``, keeps a per-worker state dict
(``get_raft_comm_state`` :266), and tears everything down in ``destroy``;
``local_handle(sessionId)`` (:247) fetches a worker's injected handle.

TPU-native mapping: JAX is SPMD-single-controller, so "workers" are mesh
devices driven by one process (or one process per host with
``jax.distributed.initialize`` playing the NCCL-uid bootstrap role —
coordinator address instead of out-of-band uid exchange).  The session
object keeps the reference's lifecycle and lookup API so consumer code
(cuML-style) ports unchanged.

Resilience (docs/FAULT_MODEL.md): the session is also the recovery
authority — the layer that owns enough context (mesh, handles, policy)
to rebuild a communicator the verbs have latched aborted.
``health_check`` runs the :mod:`~raft_tpu.comms.selftest` battery plus a
per-device liveness probe; ``recover`` rebuilds a fresh
:class:`HostComms` on the surviving sub-mesh and re-injects it on every
registered handle (the reference's analog is tearing down the Dask comms
session and re-running ``_func_init_all`` on the surviving workers).
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from raft_tpu.comms import HostComms, default_mesh, selftest
from raft_tpu.comms.resilience import RetryPolicy
from raft_tpu.core import flight as _flight
from raft_tpu.core import inventory as _inventory
from raft_tpu.core import metrics as _metrics
from raft_tpu.core import profiler as _profiler
from raft_tpu.core import tracing
from raft_tpu.core.error import CommError, expects, fail
from raft_tpu.core.handle import Handle

# module-level session registry (the reference keeps worker-local state
# dicts keyed by sessionId, comms.py:266)
_sessions: Dict[str, "Comms"] = {}


def inject_comms_on_handle(handle: Handle, comms: HostComms) -> None:
    """Attach an initialized communicator to a handle (reference
    comms_utils.pyx inject_comms_on_handle → helper.hpp:39)."""
    handle.set_comms(comms)
    handle.mesh = comms.mesh


def _distributed_is_initialized() -> bool:
    """Whether this process already joined a jax.distributed cluster.
    Private-API probe, gated: absent the attribute, assume not joined."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def _probe_device(device) -> bool:
    """Liveness probe for one device: round-trip a scalar through it.
    The per-device analog of the reference's per-worker NCCL health
    check — a device whose runtime cannot even place a scalar has left
    the mesh.  Devices owned by *other* processes cannot be probed
    locally (device_put raises for non-addressable devices, healthy or
    not); they report live here and process death is the coordination
    service's job to detect — the reference splits responsibility the
    same way (NCCL per-device checks vs. Dask worker liveness)."""
    if device.process_index != jax.process_index():
        return True
    try:
        import jax.numpy as jnp

        jax.block_until_ready(
            jax.device_put(jnp.zeros((), jnp.int32), device))
        return True
    except Exception:
        return False


class Comms:
    """Communicator session over a device mesh (reference Comms,
    python/raft/dask/common/comms.py:37).

    Parameters
    ----------
    comms_p2p:
        Whether tagged p2p will be used (the reference's UCX flag; here
        p2p rides the same XLA collectives, so this is informational).
    mesh:
        Device mesh to span; defaults to all local devices on a 1-D mesh.
    coordinator_address / num_processes / process_id:
        Multi-host bootstrap via ``jax.distributed.initialize`` — the
        NCCL-unique-id exchange analog.  Leave None for single-process.
    retry_policy:
        Optional :class:`~raft_tpu.comms.resilience.RetryPolicy` applied
        to every eager verb of the session's communicator (and its
        comm_split children) — and, unless ``bootstrap_retry_policy``
        overrides it, to the multi-host bootstrap.  None preserves
        fail-on-first-error.
    bootstrap_retry_policy:
        Optional separate policy for ``jax.distributed.initialize``.
        The two call sites want opposite timeout stances
        (docs/FAULT_MODEL.md): bootstrap connects are genuinely
        transient (``retry_timeouts=True``), while production verb
        policies should treat a timeout as fatal
        (``retry_timeouts=False``) to avoid overlapping an abandoned
        attempt with its retry on the same mesh.  Defaults to
        ``retry_policy``.
    """

    def __init__(self, comms_p2p: bool = False, mesh=None,
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 bootstrap_retry_policy: Optional[RetryPolicy] = None,
                 verbose: bool = False):
        self.comms_p2p = comms_p2p
        self.sessionId = uuid.uuid4().hex
        self._mesh = mesh
        self._coordinator = coordinator_address
        self._num_processes = num_processes
        self._process_id = process_id
        self.retry_policy = retry_policy
        self.bootstrap_retry_policy = (bootstrap_retry_policy
                                       if bootstrap_retry_policy is not None
                                       else retry_policy)
        self.verbose = verbose
        self.initialized = False
        self.comms: Optional[HostComms] = None
        self.handle: Optional[Handle] = None
        self._handles: List[Handle] = []
        self._services: Dict[str, object] = {}
        self._ops_plane = None
        self._owns_distributed = False

    # -- lifecycle (reference init/destroy, comms.py:171,228) ---------- #
    def _bootstrap_distributed(self) -> None:
        """Join the coordination service (the NCCL-uid-exchange analog),
        retried under the session policy: bootstrap failures are the
        most transient failures a cluster has (coordinator not up yet,
        DNS lag), and each attempt is bounded by the policy watchdog so
        a black-holed connect cannot hang bring-up forever."""
        if _distributed_is_initialized():
            # The user already brought the runtime up themselves: use it
            # but do NOT claim ownership — destroy() must not shut down
            # a connection this session never created.  (Known limit: a
            # watchdog-abandoned attempt from a *previous failed session*
            # that lands late is indistinguishable from a user-owned
            # runtime and is likewise adopted unowned; threads cannot be
            # cancelled, so the only airtight fix is process restart —
            # the same posture as a leaked ncclCommInitRank.)
            return

        def connect():
            # idempotency guard for the retry path: a watchdog-abandoned
            # attempt keeps running on its worker thread and may land the
            # connection after the timeout fired; jax.distributed.initialize
            # may only be called once, so a retry that finds the runtime
            # up treats that as success instead of a fresh (and fatal)
            # re-initialize.  (The runtime was down before our first
            # attempt — checked above — so any connection found here is
            # ours to own.)
            if _distributed_is_initialized():
                return
            jax.distributed.initialize(
                coordinator_address=self._coordinator,
                num_processes=self._num_processes,
                process_id=self._process_id)

        policy = self.bootstrap_retry_policy
        if policy is None:
            connect()
        else:
            try:
                policy.call(connect, verb="bootstrap")
            except Exception as e:
                raise CommError(
                    "multi-host bootstrap to %s failed after %d attempts: %s"
                    % (self._coordinator,
                       policy.max_retries + 1, e)) from e
        self._owns_distributed = True

    def init(self) -> "Comms":
        if self.initialized:
            return self
        if self._coordinator is not None:
            self._bootstrap_distributed()
        try:
            mesh = self._mesh if self._mesh is not None else default_mesh()
            self.comms = HostComms(mesh, retry_policy=self.retry_policy)
            self.handle = Handle(mesh=mesh)
            self.register_handle(self.handle)
        except Exception:
            # failure after a successful bootstrap: release the owned
            # distributed connection now — as a context manager,
            # __exit__/destroy never runs when __enter__ raises, and a
            # leaked connection would be silently adopted (unowned, so
            # never shut down) by the next session in this process
            self.destroy()
            raise
        _sessions[self.sessionId] = self
        self.initialized = True
        if self.verbose:
            print(f"Initialized comms session {self.sessionId} over "
                  f"{mesh.devices.size} devices")
        return self

    def register_handle(self, handle: Handle) -> Handle:
        """Inject the session communicator on ``handle`` and track it so
        :meth:`recover` can re-inject after a rebuild (the reference
        pattern: ``_func_init_all`` re-injects on every worker handle)."""
        expects(self.comms is not None,
                "register_handle: session has no communicator")
        inject_comms_on_handle(handle, self.comms)
        if handle not in self._handles:
            self._handles.append(handle)
        return handle

    def destroy(self) -> None:
        """Tear down and deregister (reference destroy, comms.py:228 —
        which shuts down NCCL/UCX; here the coordination service).

        Serve workers registered via :meth:`serve` are drained and
        closed FIRST: an in-flight micro-batch still running on the
        worker thread must complete (or fail onto its futures) before
        the communicator/handles it may reference are torn down —
        otherwise the batch races a destroyed handle.

        Idempotent: a second ``destroy`` (or one on a never-initialized
        session) is a no-op.  The ``_sessions`` registry entry is removed
        in a ``finally`` so a teardown failure can never leave a dead
        session shadowing a later one under the same id."""
        if not self.initialized:
            # a bootstrap that succeeded before a later init() failure
            # still owns the distributed connection — release it here or
            # the next session's initialize fails with "already
            # initialized"
            try:
                if self._owns_distributed:
                    self._teardown()
            finally:
                _sessions.pop(self.sessionId, None)
            return
        try:
            # ops plane first: scrapers must stop reading service
            # state before the services it reports on are drained
            self._close_ops_plane()
            self._close_services()
            self._teardown()
        finally:
            self.comms = None
            self.handle = None
            self._handles = []
            self._services = {}
            self.initialized = False
            _sessions.pop(self.sessionId, None)
            # the shared zeros cache (serve pad tails, comms assembly
            # blanks) has no owner of its own — session teardown is its
            # release seam; blocks are re-created on demand if another
            # live session still needs them
            try:
                from raft_tpu.mr.buffer import default_zeros_pool
                default_zeros_pool().release()
            except Exception:
                pass

    def _close_ops_plane(self) -> None:
        plane, self._ops_plane = self._ops_plane, None
        if plane is not None:
            try:
                plane.close()
            except Exception:
                pass

    def _close_services(self) -> None:
        """Drain-then-close every registered serve worker (destroy
        ordering contract above).  The drain is bounded: a device call
        wedged inside XLA must not hang ``destroy`` forever — after the
        timeout, ``close`` fails the leftovers onto their futures and
        teardown proceeds.  A service whose close raises must not block
        the teardown of the rest."""
        for svc in list(self._services.values()):
            try:
                svc.close(drain=True, timeout=10.0)
            except Exception:
                pass

    def _teardown(self) -> None:
        """Release cluster-level resources (separate from bookkeeping so
        ``destroy`` can guarantee deregistration around it)."""
        if self._owns_distributed:
            self._owns_distributed = False
            try:
                jax.distributed.shutdown()
            except Exception:
                pass

    # -- health / recovery (docs/FAULT_MODEL.md) ----------------------- #
    def health_check(self) -> Dict:
        """Run the self-test battery plus per-device liveness probes.

        Returns ``{"ok": bool, "tests": {name: bool}, "devices":
        {device_id: bool}}`` — the per-collective verdicts come from
        :func:`raft_tpu.comms.selftest.run_all` (reference test.hpp
        battery) and the per-device verdicts from a scalar round-trip on
        each mesh device.  On an aborted communicator every collective
        verdict is False (the probes fail fast) while the device probes
        still report which devices *could* carry a rebuilt communicator —
        the input :meth:`recover` needs.

        When serve workers are registered (:meth:`serve`), the verdict
        additionally carries ``"services"``: each live service's
        ``stats()`` dict — including circuit-breaker state and the last
        maintenance failure (a silently failing compaction is visible
        here).  A service that is open but whose worker thread has died
        fails the overall ``ok`` (it is silently dropping every queued
        request; ``ServeWorker.restart()`` / :meth:`self_heal` are the
        repair levers), as does an open service whose breaker is
        tripped open (it is shedding everything).  An intentionally
        closed service is reported but does not fail health.

        Cost note: the battery is not free — ``test_commsplit`` builds
        throwaway sub-communicators whose programs recompile on every
        probe.  For a recurring high-frequency probe, call a cheap
        subset directly (e.g. ``selftest.test_collective_allreduce``)
        and reserve the full battery for pre-/post-recovery checks.
        """
        expects(self.initialized, "health_check: session not initialized")
        with tracing.event("comms.health_check", "session=%s",
                           self.sessionId):
            tests = selftest.run_all(self.comms)
            devices = {int(d.id): _probe_device(d)
                       for d in self.comms.mesh.devices.ravel()}
        ok = all(tests.values()) and all(devices.values())
        out = {"ok": ok, "tests": tests, "devices": devices}
        # black-box headers (breaker trips / recoveries snapshot the
        # flight ring automatically — docs/OBSERVABILITY.md): the
        # postmortem entry point rides in the health verdict; full
        # event payloads stay in flight.default_recorder().blackboxes()
        blackboxes = _flight.default_recorder().blackbox_summaries()
        if blackboxes:
            out["flight_blackboxes"] = blackboxes
        if self._services:
            mesh_devices = set(
                int(d.id) for d in self.comms.mesh.devices.ravel())
            services = {}
            for name, svc in self._services.items():
                s = svc.stats()
                replica_ids = None
                if callable(getattr(svc, "replica_device_ids", None)):
                    replica_ids = svc.replica_device_ids()
                if replica_ids is not None:
                    # replicated service: every replica sub-mesh must
                    # still be carried by the (possibly rebuilt)
                    # session mesh — flag a stale replica span before
                    # its next dispatch breaks (rebuild_replicas via
                    # post_recover is the repair lever)
                    s["mesh_ok"] = replica_ids <= mesh_devices
                elif getattr(svc, "axis", None) is not None:
                    # validate the sharded service's mesh assumptions
                    # against the CURRENT session mesh: after recover()
                    # rebuilt the communicator on a sub-mesh, a service
                    # still sharded over the old mesh (axis gone, or
                    # spanning devices the session no longer has) would
                    # only fail at its next dispatch — flag it here so
                    # the repair lever (post_recover re-partitioning)
                    # runs before traffic does
                    s["mesh_ok"] = (
                        svc.axis in self.comms.mesh.axis_names
                        and set(int(d.id) for d in
                                svc.mesh.devices.ravel())
                        <= mesh_devices)
                services[name] = s
            out["services"] = services

            # fail health only for a service that SHOULD be serving: a
            # started worker that died, a breaker tripped open, or a
            # sharded service whose mesh no longer matches the
            # session's, while the service is still open (threadless
            # test-mode services and closed services pass)
            def _service_ok(s):
                if not s["open"]:
                    return True
                if s["worker_started"] and not s["worker_alive"]:
                    return False
                if s.get("mesh_ok") is False:
                    return False
                # detected (unrepaired) durable-state corruption
                # fails health: the scrubber found a snapshot chunk
                # or host-store slot whose bytes no longer match
                # their checksum and could not rebuild it
                # (docs/PERSISTENCE.md; snapshot staleness is
                # surfaced in stats()["persist"] but does not fail)
                if s.get("persist", {}).get("corruption_detected"):
                    return False
                br = s.get("breaker")
                return not (br and br.get("state") == "open")

            out["ok"] = ok and all(_service_ok(s)
                                   for s in services.values())
        return out

    def recover(self, devices: Optional[Sequence] = None,
                mesh=None) -> HostComms:
        """Rebuild a fresh communicator on the surviving sub-mesh and
        re-inject it on every registered handle.

        ``devices`` names the survivors explicitly — as ``jax.Device``
        objects or as the int device ids :meth:`health_check` keys its
        verdicts by; None probes every device of the current mesh and
        keeps the live ones.  The automatic
        rebuild produces a 1-D mesh over the comms axis, so a session on
        a multi-axis mesh must pass the replacement ``mesh`` explicitly —
        silently flattening away the other axes would break every
        consumer shard_mapping over them.  The old communicator —
        typically latched aborted — is discarded, its compiled programs
        with it; the new one spans only survivors, so consumers resume at
        reduced width rather than not at all (mesh-shrink degradation;
        the reference analog rebuilds the Dask comms session on the
        surviving workers).
        """
        expects(self.initialized, "recover: session not initialized")
        expects(devices is None or mesh is None,
                "recover: pass either devices or mesh, not both — an "
                "explicit mesh already names its devices")
        axis = self.comms.axis
        if mesh is None:
            expects(len(self.comms.mesh.axis_names) == 1,
                    "recover: automatic rebuild only supports 1-D meshes; "
                    "session mesh has axes %s — pass the replacement mesh "
                    "explicitly", tuple(self.comms.mesh.axis_names))
            if devices is None:
                devices = [d for d in self.comms.mesh.devices.ravel()
                           if _probe_device(d)]
            by_id = {d.id: d for d in self.comms.mesh.devices.ravel()}
            resolved = []
            for d in devices:
                key = d if isinstance(d, int) else getattr(d, "id", None)
                expects(key in by_id,
                        "recover: device %s not in the session mesh", d)
                resolved.append(by_id[key])
            devices = resolved
            expects(len(devices) >= 1, "recover: no surviving devices")
        else:
            expects(axis in mesh.axis_names,
                    "recover: replacement mesh lacks comms axis %s", axis)
            devices = list(mesh.devices.ravel())
        with tracing.event("comms.recover", "session=%s survivors=%d",
                           self.sessionId, len(devices)):
            from jax.sharding import Mesh

            if mesh is None:
                mesh = Mesh(np.asarray(devices), (axis,))
            # carry the surviving communicator's configuration across
            # the rebuild — dropping p2p_staging here would silently
            # revert a pinned staging mode to the "device" default
            # (comm_split forwards it for the same reason)
            self.comms = HostComms(
                mesh, axis, retry_policy=self.retry_policy,
                p2p_staging=getattr(self.comms, "p2p_staging", "device"))
            self._mesh = mesh
            for h in self._handles:
                inject_comms_on_handle(h, self.comms)
        if self.verbose:
            print(f"Recovered comms session {self.sessionId} on "
                  f"{len(devices)} surviving devices")
        return self.comms

    def self_heal(self, **recover_kwargs) -> Dict:
        """Health-check, and if anything is wrong — aborted
        communicator, dead device, dead worker thread, tripped breaker
        — run the full serving recovery sequence
        (:class:`raft_tpu.serve.resilience.RecoveryManager`): pause
        admission, quiesce in-flight batches, rebuild the communicator
        on the devices the check reported live, re-publish service
        state and re-run ``warmup()``, restart dead workers, re-admit.

        Returns ``{"report": health_check dict, "recovered": bool,
        "recovery": recover report or None}``.  Call from a supervising
        thread (operator loop / chaos harness), never from a serve
        worker.  ``recover_kwargs`` forward to
        :meth:`RecoveryManager.recover` (``devices=`` / ``mesh=``
        override the probed survivor list)."""
        expects(self.initialized, "self_heal: session not initialized")
        from raft_tpu.serve.resilience import RecoveryManager

        return RecoveryManager(self).check_and_recover(**recover_kwargs)

    # -- serving (docs/SERVING.md) ------------------------------------- #
    def serve(self, kind: str = "knn", *, name: Optional[str] = None,
              **kwargs):
        """Construct and register a micro-batching service on this
        session (:mod:`raft_tpu.serve`).

        ``kind``: ``"knn"`` (:class:`~raft_tpu.serve.KNNService`;
        kwargs: ``index``, ``k``, ``metric``, ...), ``"pairwise"``
        (:class:`~raft_tpu.serve.PairwiseService`; kwargs: ``y``,
        ``metric``, ...) or ``"ann"``
        (:class:`~raft_tpu.serve.ANNService`; kwargs: a prebuilt IVF
        ``index``, ``k``, ``nprobe``, ``delta_cap``, ...), plus the
        shared service options (``max_batch_rows``, ``bucket_rungs``,
        ``max_wait_ms``, ``queue_cap``, ``retry_policy``,
        ``tenant_weights``, ``query_cache_size``).  The session
        defaults ``retry_policy`` to its own verb policy so per-batch
        watchdog/retry semantics match the communicator's.

        ``serve(kind="knn", replicas=R, ...)`` builds R replicas of
        the service over disjoint sub-meshes of the session mesh with
        hedged dispatch of straggling batches (docs/SERVING.md
        "Traffic shaping"); ``health_check`` validates every replica's
        devices against the session mesh and ``post_recover`` re-cuts
        the groups after a mesh rebuild.

        ``serve(kind="ann", persist_dir=...)`` passes the durability
        knobs straight through (docs/PERSISTENCE.md): the service
        auto-restores from the directory on construction, journals
        every acknowledged insert, snapshots on its maintenance seam,
        and ``health_check`` fails ``ok`` when the integrity scrubber
        detects unrepaired corruption (surfaced in
        ``stats()["persist"]`` alongside snapshot staleness).

        Registration is what buys the lifecycle guarantees:
        :meth:`health_check` reports the service and :meth:`destroy`
        drains it before comms teardown — for an ANN service the drain
        also closes out compaction: the worker thread that runs
        maintenance is joined, so no index swap is mid-flight when the
        communicator goes down (and a persistent service takes its
        final snapshot).  The returned service is started; call
        ``warmup()`` before taking traffic to precompile every shape
        bucket (× nprobe cell for ANN).
        """
        expects(self.initialized, "serve: session not initialized")
        from raft_tpu.serve import ANNService, KNNService, PairwiseService

        kinds = {"knn": KNNService, "pairwise": PairwiseService,
                 "ann": ANNService}
        expects(kind in kinds, "serve: unknown service kind %r "
                "(have: %s)", kind, ", ".join(sorted(kinds)))
        expects(name is None or name not in self._services,
                "serve: a service named %r is already registered", name)
        kwargs.setdefault("retry_policy", self.retry_policy)
        if ((kwargs.get("axis") is not None
             or kwargs.get("replicas") is not None)
                and kwargs.get("mesh") is None):
            # sharded/replicated service on the session: span THE
            # session mesh (docs/SERVING.md "Sharded serving"/"Traffic
            # shaping") so recover() / post_recover re-partitioning and
            # health_check mesh validation all talk about the same mesh
            kwargs["mesh"] = self.comms.mesh
        svc = kinds[kind](name=name, **kwargs)
        # bind the owning session: sharded services re-partition onto
        # the session's (possibly rebuilt) mesh in post_recover
        svc._session = self
        if svc.name in self._services:
            # auto-generated name collided: stop the just-started
            # worker before raising or it leaks, unregistered and
            # undrainable
            svc.close(drain=False)
            fail("serve: a service named %r is already registered",
                 svc.name)
        self._services[svc.name] = svc
        return svc

    @property
    def services(self) -> Dict[str, object]:
        """Registered serve services by name (read-only view)."""
        return dict(self._services)

    def serve_ops(self, port: int = 0, **kwargs):
        """Start the embedded ops plane over this session
        (docs/OBSERVABILITY.md "Ops plane"): an HTTP endpoint on a
        daemon thread serving ``/metrics`` (Prometheus), ``/healthz``
        (cheap liveness + the anomaly sentinel's degraded flag;
        ``?full=1`` runs the session battery behind a TTL cache),
        ``/statusz``, ``/debug/traces``, ``/debug/config``,
        ``/debug/inventory``, ``/debug/snapshot`` and
        ``POST /debug/blackbox``.  Every handler reads immutable
        host-side snapshots — a scrape can never compile or perturb
        serving (the static no-jax ban, ``ci/style_check.py``).

        ``port=0`` binds an ephemeral port (read ``plane.port``);
        ``kwargs`` forward to
        :class:`~raft_tpu.serve.opsplane.OpsPlane` (``host=``,
        ``sentinel=``, ``healthz_ttl_s=``, ...).  One plane per
        session; :meth:`destroy` closes it before draining services.
        """
        expects(self.initialized, "serve_ops: session not initialized")
        # a manually closed plane must not brick the session: only a
        # LIVE plane blocks a second one
        expects(self._ops_plane is None or self._ops_plane.closed,
                "serve_ops: this session already has a live ops "
                "plane (close it first)")
        from raft_tpu.serve.opsplane import OpsPlane

        self._ops_plane = OpsPlane(session=self, port=port, **kwargs)
        return self._ops_plane

    @property
    def ops_plane(self):
        """The session's live ops plane, or None."""
        return self._ops_plane

    # -- observability (docs/OBSERVABILITY.md) ------------------------- #
    def metrics_snapshot(self) -> Dict:
        """One self-contained observability artifact for this process:

        - ``metrics``: the default registry snapshot — per-primitive
          timer histograms (``raft_tpu_<layer>_*_seconds``), comms
          bytes/latency per verb, memory gauges with peaks;
        - ``compile_cache``: per-(fn, shape) jit hit/miss/compile-
          seconds attribution (:func:`profiler.compile_cache_stats`);
        - ``profiler_tree`` / ``profiler_report``: the nested span tree
          (dict form and the human-readable rendering);
        - ``event_counters``: PR 1's resilience counters
          (:func:`tracing.counters`).

        Works on an uninitialized session too — the metrics are
        process-global; the session is just the natural owner of "give
        me the run's numbers" (the reference's analog would be asking
        the Dask comms session for cluster state).  Session-free
        callers (bench, tools) use the module-level
        :func:`metrics_snapshot`.
        """
        return metrics_snapshot()

    def dump_metrics(self, path: str) -> Dict:
        """Write :meth:`metrics_snapshot` as JSON to ``path`` (the
        artifact ``tools/metrics_report.py`` and the bench attach);
        returns the snapshot that was written."""
        import json

        snap = self.metrics_snapshot()
        with open(path, "w", encoding="utf-8") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
            f.write("\n")
        return snap

    def worker_info(self, workers=None) -> Dict:
        """Rank/device map per "worker" (reference Comms.worker_info,
        comms.py:154, which maps each Dask worker to its NCCL rank and
        UCX port).  Here a worker is a mesh device: the map is keyed by
        device id and carries the *communicator* rank — the device's
        coordinate along the comms axis, the same rank space
        ``lax.axis_index(comms.axis)`` reports in-trace — plus its
        position on any
        other mesh axes, process index, and platform.  ``workers``
        optionally restricts to those device ids."""
        expects(self.initialized, "worker_info: session not initialized")
        mesh = self.comms.mesh
        axis_idx = mesh.axis_names.index(self.comms.axis)
        info = {}
        for coords in np.ndindex(*mesh.devices.shape):
            d = mesh.devices[coords]
            if workers is not None and d.id not in workers:
                continue
            info[d.id] = {"rank": int(coords[axis_idx]),
                          "mesh_coords": dict(zip(mesh.axis_names,
                                                  map(int, coords))),
                          "process_index": d.process_index,
                          "platform": d.platform,
                          "device_kind": d.device_kind}
        return info

    def __enter__(self) -> "Comms":
        return self.init()

    def __exit__(self, *exc) -> None:
        self.destroy()


# the ISSUE-2 observability surface names the session object "Session";
# `Comms` keeps the reference's name — same class
Session = Comms


def metrics_snapshot() -> Dict:
    """Process-global observability snapshot (see
    :meth:`Comms.metrics_snapshot` for the field inventory)."""
    # flight recorder state (docs/OBSERVABILITY.md "Flight recorder &
    # request tracing"): ring occupancy, black-box headers, per-service
    # SLO burn state, slowest exemplars — rides into every bench
    # artifact alongside the metrics.  Taken FIRST: snapshotting the
    # SLO trackers publishes their gauges, which the registry snapshot
    # below must already see.
    fl = _flight.flight_snapshot()
    # program cost inventory (docs/OBSERVABILITY.md "Ops plane"):
    # per-executable flops/bytes/footprint summary + full detail —
    # after warmup this is the complete serving working set
    inv = _inventory.summary()
    inv["detail"] = _inventory.snapshot()
    return {
        "metrics": _metrics.default_registry().snapshot(),
        "compile_cache": _profiler.compile_cache_stats(),
        "profiler_tree": _profiler.default_profiler().tree(),
        "profiler_report": _profiler.default_profiler().report(),
        "event_counters": tracing.counters(),
        "flight": fl,
        "inventory": inv,
    }


def get_raft_comm_state(session_id: str) -> Dict:
    """Session state dict (reference get_raft_comm_state, comms.py:266)."""
    s = _sessions.get(session_id)
    if s is None:
        return {}
    return {"sessionId": s.sessionId, "comms": s.comms,
            "handle": s.handle, "nworkers": s.comms.get_size()}


def local_handle(session_id: str) -> Handle:
    """Fetch the session's injected handle (reference local_handle,
    comms.py:247)."""
    s = _sessions.get(session_id)
    expects(s is not None and s.initialized,
            "local_handle: no initialized session %s", session_id)
    return s.handle
