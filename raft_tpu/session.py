"""Cluster session orchestration: the Dask/`Comms` lifecycle, TPU-native.

Reference: python/raft/dask/common/comms.py — the ``Comms`` session object
(:37) generates an NCCL unique id (:136-169), runs ``_func_init_all`` on
every Dask worker (:414-460) to init NCCL/UCX and
``inject_comms_on_handle``, keeps a per-worker state dict
(``get_raft_comm_state`` :266), and tears everything down in ``destroy``;
``local_handle(sessionId)`` (:247) fetches a worker's injected handle.

TPU-native mapping: JAX is SPMD-single-controller, so "workers" are mesh
devices driven by one process (or one process per host with
``jax.distributed.initialize`` playing the NCCL-uid bootstrap role —
coordinator address instead of out-of-band uid exchange).  The session
object keeps the reference's lifecycle and lookup API so consumer code
(cuML-style) ports unchanged.
"""

from __future__ import annotations

import uuid
from typing import Dict, Optional

import jax

from raft_tpu.comms import HostComms, default_mesh
from raft_tpu.core.error import expects
from raft_tpu.core.handle import Handle

# module-level session registry (the reference keeps worker-local state
# dicts keyed by sessionId, comms.py:266)
_sessions: Dict[str, "Comms"] = {}


def inject_comms_on_handle(handle: Handle, comms: HostComms) -> None:
    """Attach an initialized communicator to a handle (reference
    comms_utils.pyx inject_comms_on_handle → helper.hpp:39)."""
    handle.set_comms(comms)
    handle.mesh = comms.mesh


class Comms:
    """Communicator session over a device mesh (reference Comms,
    python/raft/dask/common/comms.py:37).

    Parameters
    ----------
    comms_p2p:
        Whether tagged p2p will be used (the reference's UCX flag; here
        p2p rides the same XLA collectives, so this is informational).
    mesh:
        Device mesh to span; defaults to all local devices on a 1-D mesh.
    coordinator_address / num_processes / process_id:
        Multi-host bootstrap via ``jax.distributed.initialize`` — the
        NCCL-unique-id exchange analog.  Leave None for single-process.
    """

    def __init__(self, comms_p2p: bool = False, mesh=None,
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 verbose: bool = False):
        self.comms_p2p = comms_p2p
        self.sessionId = uuid.uuid4().hex
        self._mesh = mesh
        self._coordinator = coordinator_address
        self._num_processes = num_processes
        self._process_id = process_id
        self.verbose = verbose
        self.initialized = False
        self.comms: Optional[HostComms] = None
        self.handle: Optional[Handle] = None
        self._owns_distributed = False

    # -- lifecycle (reference init/destroy, comms.py:171,228) ---------- #
    def init(self) -> "Comms":
        if self.initialized:
            return self
        if self._coordinator is not None:
            # multi-host bring-up: coordination service replaces the
            # out-of-band NCCL uid exchange (SURVEY.md §3.3)
            jax.distributed.initialize(
                coordinator_address=self._coordinator,
                num_processes=self._num_processes,
                process_id=self._process_id)
            self._owns_distributed = True
        mesh = self._mesh if self._mesh is not None else default_mesh()
        self.comms = HostComms(mesh)
        self.handle = Handle(mesh=mesh)
        inject_comms_on_handle(self.handle, self.comms)
        _sessions[self.sessionId] = self
        self.initialized = True
        if self.verbose:
            print(f"Initialized comms session {self.sessionId} over "
                  f"{mesh.devices.size} devices")
        return self

    def destroy(self) -> None:
        """Tear down and deregister (reference destroy, comms.py:228 —
        which shuts down NCCL/UCX; here the coordination service)."""
        _sessions.pop(self.sessionId, None)
        self.comms = None
        self.handle = None
        if self._owns_distributed:
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            self._owns_distributed = False
        self.initialized = False

    def worker_info(self, workers=None) -> Dict:
        """Rank/device map per "worker" (reference Comms.worker_info,
        comms.py:154, which maps each Dask worker to its NCCL rank and
        UCX port).  Here a worker is a mesh device: the map is keyed by
        device id and carries the *communicator* rank — the device's
        coordinate along the comms axis, the same rank space
        ``lax.axis_index(comms.axis)`` reports in-trace — plus its
        position on any
        other mesh axes, process index, and platform.  ``workers``
        optionally restricts to those device ids."""
        import numpy as np

        expects(self.initialized, "worker_info: session not initialized")
        mesh = self.comms.mesh
        axis_idx = mesh.axis_names.index(self.comms.axis)
        info = {}
        for coords in np.ndindex(*mesh.devices.shape):
            d = mesh.devices[coords]
            if workers is not None and d.id not in workers:
                continue
            info[d.id] = {"rank": int(coords[axis_idx]),
                          "mesh_coords": dict(zip(mesh.axis_names,
                                                  map(int, coords))),
                          "process_index": d.process_index,
                          "platform": d.platform,
                          "device_kind": d.device_kind}
        return info

    def __enter__(self) -> "Comms":
        return self.init()

    def __exit__(self, *exc) -> None:
        self.destroy()


def get_raft_comm_state(session_id: str) -> Dict:
    """Session state dict (reference get_raft_comm_state, comms.py:266)."""
    s = _sessions.get(session_id)
    if s is None:
        return {}
    return {"sessionId": s.sessionId, "comms": s.comms,
            "handle": s.handle, "nworkers": s.comms.get_size()}


def local_handle(session_id: str) -> Handle:
    """Fetch the session's injected handle (reference local_handle,
    comms.py:247)."""
    s = _sessions.get(session_id)
    expects(s is not None and s.initialized,
            "local_handle: no initialized session %s", session_id)
    return s.handle
