"""Set-associative vector cache (reference cpp/include/raft/cache/)."""

from raft_tpu.cache.cache import VecCache  # noqa: F401
