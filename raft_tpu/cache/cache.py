"""LRU set-associative device cache for feature vectors.

Reference: cache/cache_util.cuh — ``get_vecs``/``get_cache_idx`` (:45),
``store_vecs`` (:86), ``rank_set_entries`` (:205), ``assign_cache_idx``
(:259) and the owning ``cache`` class (cache/cache.cuh).  The reference
keeps an (n_vec × cache_size) column-major buffer, maps key → set =
key % n_sets, and evicts the least-recently-used way per set.

TPU design: the cache is a small pytree of device arrays (vectors, keys,
timestamps); lookup is a vectorized equality scan over the key table (sets
× ways is small), and eviction is an argmin over per-way timestamps — all
branch-free gathers/scatters, jit-friendly.  State is carried functionally
(each op returns the new cache), matching JAX's update-in-place donation
model rather than the reference's mutable buffers.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class CacheState(NamedTuple):
    vectors: jnp.ndarray   # (n_sets, associativity, n_dim)
    keys: jnp.ndarray      # (n_sets, associativity) int32, -1 = empty
    time: jnp.ndarray      # (n_sets, associativity) int32 LRU stamps
    clock: jnp.ndarray     # () int32 global counter


class VecCache:
    """Functional set-associative vector cache (reference cache.cuh:40).

    Parameters
    ----------
    n_dim: vector dimensionality.
    n_vecs: cache capacity in vectors (rounded down to a multiple of
        ``associativity``; the reference uses cache_size in MiB — callers
        can convert).
    associativity: ways per set (reference ``associativity`` = 32).
    """

    def __init__(self, n_dim: int, n_vecs: int, associativity: int = 32,
                 dtype=jnp.float32):
        self.n_dim = n_dim
        self.assoc = min(associativity, max(n_vecs, 1))
        self.n_sets = max(n_vecs // self.assoc, 1)
        self.dtype = dtype

    def init(self) -> CacheState:
        return CacheState(
            vectors=jnp.zeros((self.n_sets, self.assoc, self.n_dim),
                              self.dtype),
            keys=jnp.full((self.n_sets, self.assoc), -1, jnp.int32),
            time=jnp.zeros((self.n_sets, self.assoc), jnp.int32),
            clock=jnp.int32(0),
        )

    # ------------------------------------------------------------------ #
    def get_vecs(self, state: CacheState, keys: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, CacheState]:
        """Fetch vectors for ``keys`` (reference get_vecs, cache_util.cuh:45).

        Returns (vectors (m, n_dim), found (m,) bool, state with refreshed
        LRU stamps).  Missing keys return zero vectors.
        """
        sets = (keys % self.n_sets).astype(jnp.int32)
        set_keys = state.keys[sets]                      # (m, assoc)
        hit = set_keys == keys[:, None].astype(jnp.int32)
        way = jnp.argmax(hit, axis=1)
        found = jnp.any(hit, axis=1)
        vecs = state.vectors[sets, way]
        vecs = jnp.where(found[:, None], vecs, 0)
        # refresh LRU stamps of hits
        new_clock = state.clock + 1
        stamped = state.time.at[sets, way].max(
            jnp.where(found, new_clock, 0))
        return vecs, found, state._replace(time=stamped, clock=new_clock)

    def store_vecs(self, state: CacheState, keys: jnp.ndarray,
                   vecs: jnp.ndarray) -> CacheState:
        """Insert vectors (reference assign_cache_idx + store_vecs,
        cache_util.cuh:259,86): keys mapping to the same set within one
        call take successive least-recently-used ways (the
        ``rank_set_entries`` ranking, :205); an existing key updates its
        own way.  Duplicate *keys* in one call: last write wins.
        """
        m = keys.shape[0]
        sets = (keys % self.n_sets).astype(jnp.int32)
        set_keys = state.keys[sets]
        hit = set_keys == keys[:, None].astype(jnp.int32)
        # rank of each *miss* key within its set group for this call (hit
        # keys use their own way and must not consume LRU slots)
        any_hit_pre = jnp.any(hit, axis=1)
        order = jnp.argsort(sets, stable=True)
        sorted_sets = sets[order]
        miss_sorted = (~any_hit_pre[order]).astype(jnp.int32)
        first = jnp.concatenate([jnp.array([True]),
                                 sorted_sets[1:] != sorted_sets[:-1]])
        incl = jnp.cumsum(miss_sorted)
        # exclusive miss-count at each group start, propagated forward
        base = jax.lax.associative_scan(
            jnp.maximum, jnp.where(first, incl - miss_sorted, 0))
        rank_sorted = (incl - miss_sorted - base).astype(jnp.int32)
        rank = jnp.zeros((m,), jnp.int32).at[order].set(rank_sorted)
        # ways of each set ordered least-recently-used first; ways already
        # claimed by hit keys in this call are marked most-recent (sorted
        # last) and misses wrap only among the remaining free ways, so a
        # new key never collides with — or, at overcapacity, evicts — an
        # entry refreshed by the same store_vecs call unless every way of
        # the set was hit
        any_hit = any_hit_pre
        hit_way = jnp.argmax(hit, axis=1).astype(jnp.int32)
        big = jnp.iinfo(jnp.int32).max
        time_adj = state.time.at[sets, hit_way].max(
            jnp.where(any_hit, big, -1))
        # hits per set in this call = number of *distinct ways* hit (a
        # duplicate hit key must not be double-counted)
        hit_mark = jnp.zeros((self.n_sets, self.assoc), jnp.int32).at[
            sets, hit_way].max(any_hit.astype(jnp.int32))
        hits_per_set = jnp.sum(hit_mark, axis=1)
        free_ways = jnp.maximum(self.assoc - hits_per_set[sets], 1)
        lru_order = jnp.argsort(time_adj[sets], axis=1)
        lru_way = jnp.take_along_axis(
            lru_order, (rank % free_ways)[:, None], axis=1)[:, 0]
        way = jnp.where(jnp.any(hit, axis=1), jnp.argmax(hit, axis=1),
                        lru_way).astype(jnp.int32)
        new_clock = state.clock + 1
        return CacheState(
            vectors=state.vectors.at[sets, way].set(vecs.astype(self.dtype)),
            keys=state.keys.at[sets, way].set(keys.astype(jnp.int32)),
            time=state.time.at[sets, way].set(new_clock),
            clock=new_clock,
        )
