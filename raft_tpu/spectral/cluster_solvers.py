"""Cluster solver facade (reference spectral/cluster_solvers.hpp).

``cluster_solver_config_t`` (:28) + ``kmeans_solver_t`` (:38) — the
pluggable clustering stage of spectral partition/modularity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp

from raft_tpu.spectral.kmeans import kmeans


@dataclass
class ClusterSolverConfig:
    """(reference cluster_solver_config_t, cluster_solvers.hpp:28)"""

    n_clusters: int
    max_iter: int = 300
    tol: float = 1e-4
    seed: int = 123456
    # spectral embeddings are tiny (n × n_eig_vecs) but rich in Lloyd
    # local optima; restarts are nearly free there and the best-of rule
    # is what the residual exists for
    n_init: int = 8


class KmeansSolver:
    """(reference kmeans_solver_t, cluster_solvers.hpp:38)"""

    def __init__(self, config: ClusterSolverConfig):
        self.config = config

    def solve(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Cluster rows of obs; returns (labels, residual, iters)."""
        c = self.config
        res = kmeans(obs, c.n_clusters, tol=c.tol, max_iter=c.max_iter,
                     seed=c.seed, n_init=c.n_init)
        return res.labels, res.residual, res.iters
