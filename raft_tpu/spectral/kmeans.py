"""k-means clustering with k-means++ initialization.

Reference: the full k-means inside spectral/kmeans.hpp —
``chooseNewCentroid`` (:349, weighted sampling by min-dist²),
``initializeCentroids`` (k-means++ loop, :446), ``assignCentroids``
(:557), ``updateCentroids`` (:628), public ``kmeans`` (:775,941).

TPU design: assignment is an (n, k) fused distance matmul on the MXU
(argmin over the expanded ‖x‖²+‖c‖²−2x·c form); the update is one
segment-sum; the k-means++ loop is a ``lax.fori_loop`` with categorical
sampling — the whole solve jit-compiles to a single XLA program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from raft_tpu.core.debug import check_finite
from raft_tpu.core.profiler import profiled, profiled_jit
from raft_tpu.core.error import expects


class KmeansResult(NamedTuple):
    centroids: jnp.ndarray  # (k, d)
    labels: jnp.ndarray     # (n,) int32
    residual: jnp.ndarray   # sum of squared distances to assigned centroid
    iters: jnp.ndarray      # Lloyd iterations executed


def _sq_dists(X, C, xn):
    """(n, k) squared distances, expanded form on the MXU."""
    cn = jnp.sum(C * C, axis=1)
    d = xn[:, None] + cn[None, :] - 2.0 * jnp.matmul(
        X, C.T, precision="highest")
    return jnp.maximum(d, 0.0)


def init_plus_plus(X: jnp.ndarray, k: int, key: jax.Array) -> jnp.ndarray:
    """k-means++ seeding (reference initializeCentroids, kmeans.hpp:446;
    chooseNewCentroid :349 samples ∝ min-dist²)."""
    n, d = X.shape
    xn = jnp.sum(X * X, axis=1)
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    C0 = jnp.zeros((k, d), X.dtype).at[0].set(X[first])
    d0 = jnp.sum((X - X[first]) ** 2, axis=1)

    def body(i, carry):
        C, dists, key = carry
        key, sub = jax.random.split(key)
        # categorical ∝ dists (all-zero dists → uniform)
        total = jnp.sum(dists)
        logits = jnp.where(total > 0,
                           jnp.log(jnp.maximum(dists, 1e-30)),
                           jnp.zeros_like(dists))
        idx = jax.random.categorical(sub, logits)
        C = C.at[i].set(X[idx])
        dists = jnp.minimum(dists, jnp.sum((X - X[idx]) ** 2, axis=1))
        return C, dists, key

    C, _, _ = jax.lax.fori_loop(1, k, body, (C0, d0, key))
    return C


@profiled_jit(name="kmeans", static_argnames=("k", "max_iter", "n_init"))
def _kmeans_jit(X, k, tol, max_iter, seed, n_init=1):
    n, d = X.shape
    xn = jnp.sum(X * X, axis=1)
    key = jax.random.PRNGKey(seed)

    def assign(C):
        if k >= 256:
            # large quantizers (IVF builds: nlist ~ sqrt(n)) must not
            # materialize the (n, k) matrix — 4 GB at n=1M, k=1024.  The
            # fused 1-NN matches argmin's smaller-index tie rule; on TPU
            # the Pallas kernel keeps the tile VMEM-resident, and the
            # explicit tile_n bounds the XLA fallback's high-water at
            # O(n * 512) so the optimization isn't backend-dependent
            from raft_tpu.distance import fused_l2_nn

            vals, labels = fused_l2_nn(X, C, tile_n=512)
            return labels, jnp.sum(vals)
        dm = _sq_dists(X, C, xn)
        labels = jnp.argmin(dm, axis=1).astype(jnp.int32)
        # row-min, NOT take_along_axis(labels): the per-row gather
        # lowers to a serial scalar loop on TPU (r4 tile-merge finding)
        # and min(dm) is by definition the labeled entry
        residual = jnp.sum(jnp.min(dm, axis=1))
        return labels, residual

    def update(C, labels):
        sums = jax.ops.segment_sum(X, labels, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((n,), X.dtype), labels,
                                     num_segments=k)
        # empty clusters keep their previous centroid
        newC = jnp.where(counts[:, None] > 0,
                         sums / jnp.maximum(counts, 1.0)[:, None], C)
        return newC

    def cond(state):
        _, _, prev_res, res, it = state
        return (it < max_iter) & (jnp.abs(prev_res - res) >
                                  tol * jnp.maximum(res, 1e-30))

    def body(state):
        C, labels, _, res, it = state
        C = update(C, labels)
        labels, new_res = assign(C)
        return C, labels, res, new_res, it + 1

    def one_solve(sub):
        C0 = init_plus_plus(X, k, sub)
        labels0, res0 = assign(C0)
        return jax.lax.while_loop(
            cond, body, (C0, labels0, jnp.inf, res0, jnp.int32(0)))

    # restarts as ONE fori_loop over the solve body (traced once
    # regardless of n_init), keeping the lowest-residual run — Lloyd's
    # local optima are real on whitened spectral embeddings, where a
    # bad k-means++ draw can split along an uninformative coordinate.
    # t=0 consumes `key` itself (not fold_in(key, 0)): keeps the
    # n_init=1 draw identical to the historical single-init solver, so
    # quantizer builds and their recall characteristics are unchanged.
    def restart(t, best):
        bC, bl, br, bi = best
        sub = jnp.where(t == 0, key, jax.random.fold_in(key, t))
        nC, nl, _, nr, ni = one_solve(sub)
        # NaN-safe best-of: `nr < br` alone would let a NaN solve lose
        # every comparison and silently return the zero-initialized
        # best (all-zero centroids/labels masquerading as a valid
        # clustering).  A finite run beats any non-finite best; when
        # both are non-finite the new one replaces the inf sentinel so
        # an all-NaN solve stays VISIBLE in the returned residual.
        take = ((nr < br)
                | (jnp.isfinite(nr) & ~jnp.isfinite(br))
                | (~jnp.isfinite(nr) & ~jnp.isfinite(br)))
        return (jnp.where(take, nC, bC), jnp.where(take, nl, bl),
                jnp.where(take, nr, br), jnp.where(take, ni, bi))

    best0 = (jnp.zeros((k, d), X.dtype), jnp.zeros((n,), jnp.int32),
             jnp.asarray(jnp.inf, X.dtype), jnp.int32(0))
    C, labels, res, iters = jax.lax.fori_loop(0, n_init, restart, best0)
    return C, labels, res, iters


@profiled("spectral")
def kmeans(X: jnp.ndarray, k: int, tol: float = 1e-4,
           max_iter: int = 300, seed: int = 1234567,
           n_init: int = 1) -> KmeansResult:
    """Lloyd k-means with k-means++ init (reference kmeans, kmeans.hpp:775).

    Returns (centroids (k, d), labels (n,), residual, iters); ``residual``
    is the total within-cluster squared distance (reference
    ``residual_host``).  ``n_init`` > 1 repeats the whole solve from
    fresh k-means++ draws and keeps the lowest-residual run (the
    spectral cluster solver's default; quantizer builds keep 1).
    """
    X = jnp.asarray(X)
    expects(X.ndim == 2, "kmeans: 2-D observations required")
    expects(1 <= k <= X.shape[0],
            "kmeans: k=%d out of range for %d points", k, X.shape[0])
    expects(n_init >= 1, "kmeans: n_init must be >= 1, got %d", n_init)
    check_finite(X, "kmeans observations")  # opt-in sanitizer, SURVEY §5
    C, labels, res, iters = _kmeans_jit(X, k, tol, max_iter, seed,
                                        n_init=n_init)
    check_finite(C, "kmeans centroids")
    return KmeansResult(C, labels, res, iters)
