"""Spectral utilities: eigenvector whitening, cluster indicators.

Reference: spectral/spectral_util.hpp — ``transform_eigen_matrix`` (:109,
per-column mean-center + scale to std·√n = 1) and ``construct_indicator``
(:44, normalized cluster indicator vector + quadratic form).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def transform_eigen_matrix(eig_vecs: jnp.ndarray) -> jnp.ndarray:
    """Whiten eigenvector columns: subtract the column mean, divide by
    (column norm / √n) (reference transform_eigen_matrix,
    spectral_util.hpp:118-145; the trailing transpose is a cuBLAS layout
    detail we don't need).

    Columns that are numerically CONSTANT (centered norm ≲ 1e-3 of the
    raw norm — e.g. the trivial all-ones Laplacian eigenvector) are
    zeroed rather than standardized: dividing f32 eigensolver noise by
    its own tiny norm would hand k-means a unit-variance garbage
    coordinate that can dominate the informative ones."""
    n = eig_vecs.shape[0]
    centered = eig_vecs - jnp.mean(eig_vecs, axis=0, keepdims=True)
    raw = jnp.linalg.norm(eig_vecs, axis=0, keepdims=True)
    norms = jnp.linalg.norm(centered, axis=0, keepdims=True)
    degenerate = norms <= 1e-3 * jnp.maximum(raw, jnp.finfo(
        eig_vecs.dtype).tiny)
    scale = norms / jnp.sqrt(jnp.asarray(n, eig_vecs.dtype))
    out = centered / jnp.where(scale == 0, 1.0, scale)
    return jnp.where(degenerate, 0.0, out)


def construct_indicator(cluster_id: int, labels: jnp.ndarray, op
                        ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """0/1 indicator x_c of one cluster + its quadratic form xᵀ(op)x
    (reference construct_indicator, spectral_util.hpp:195-225 — the
    indicator is *unnormalized*; partStats = part_iᵀ B part_i).

    Returns (cluster_size, quad_form, valid) — valid False for an empty
    cluster (the reference returns false and warns).
    """
    part = (labels == cluster_id).astype(jnp.float32)
    size = jnp.sum(part)
    quad = jnp.dot(part, op.mv(part))
    return size, quad, size > 0
