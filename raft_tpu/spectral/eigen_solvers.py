"""Eigen solver facade over the thick-restart Lanczos driver.

Reference: spectral/eigen_solvers.hpp — ``eigen_solver_config_t`` (:27),
``lanczos_solver_t`` (:42) delegating to linalg/lanczos.hpp.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.profiler import profiled

from raft_tpu.linalg.lanczos import (
    compute_largest_eigenvectors,
    compute_smallest_eigenvectors,
)


@dataclass
class EigenSolverConfig:
    """(reference eigen_solver_config_t, eigen_solvers.hpp:27)"""

    n_eig_vecs: int
    max_iter: int = 4000
    restart_iter: int = 0
    tol: float = 1e-9
    reorthogonalize: bool = True  # thick-restart driver always does
    seed: int = 1234567


class LanczosSolver:
    """(reference lanczos_solver_t, eigen_solvers.hpp:42)"""

    def __init__(self, config: EigenSolverConfig):
        self.config = config

    @profiled("spectral", "lanczos_smallest")
    def solve_smallest_eigenvectors(self, op, n: int
                                    ) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
        c = self.config
        mv = op.mv if hasattr(op, "mv") else op
        return compute_smallest_eigenvectors(
            mv, n, c.n_eig_vecs, maxiter=c.max_iter,
            restart_iter=c.restart_iter, tol=c.tol, seed=c.seed)

    @profiled("spectral", "lanczos_largest")
    def solve_largest_eigenvectors(self, op, n: int
                                   ) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
        c = self.config
        mv = op.mv if hasattr(op, "mv") else op
        return compute_largest_eigenvectors(
            mv, n, c.n_eig_vecs, maxiter=c.max_iter,
            restart_iter=c.restart_iter, tol=c.tol, seed=c.seed)
