"""Spectral graph analysis: implicit operators, eigen/cluster solvers,
graph partitioning, modularity maximization.

Reference: cpp/include/raft/spectral/ (2,794 LoC) — see SURVEY.md §2.7.
"""

from raft_tpu.spectral.matrix_wrappers import (  # noqa: F401
    SparseMatrix, LaplacianMatrix, ModularityMatrix,
)
from raft_tpu.spectral.eigen_solvers import (  # noqa: F401
    EigenSolverConfig, LanczosSolver,
)
from raft_tpu.spectral.kmeans import kmeans  # noqa: F401
from raft_tpu.spectral.cluster_solvers import (  # noqa: F401
    ClusterSolverConfig, KmeansSolver,
)
from raft_tpu.spectral.partition import partition, analyze_partition  # noqa: F401
from raft_tpu.spectral.modularity_maximization import (  # noqa: F401
    modularity_maximization, analyze_modularity,
)
