"""Implicit matrix operators for spectral methods.

Reference: spectral/matrix_wrappers.hpp — ``sparse_matrix_t`` with cuSPARSE
``mv()`` (:126,180), ``laplacian_matrix_t`` (D−A as an implicit operator,
:300), ``modularity_matrix_t`` (A − d dᵀ/2E, :372).

TPU design: operators are lightweight pytrees exposing ``mv(x)``; the SpMV
is the gather + segment-sum kernel (sparse/linalg.py), and the Laplacian /
modularity corrections are rank-1 vector updates fused by XLA.  Everything
stays functional so an operator can be closed over inside ``jit`` (the
Lanczos driver takes ``mv`` as a callable).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.formats import CSR
from raft_tpu.sparse.linalg import csr_spmv


@jax.tree_util.register_pytree_node_class
class SparseMatrix:
    """CSR operator with ``mv`` (reference sparse_matrix_t, :126)."""

    def __init__(self, csr: CSR):
        self.csr = csr

    def tree_flatten(self):
        return (self.csr,), ()

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    @property
    def n_rows(self) -> int:
        return self.csr.n_rows

    def mv(self, x: jnp.ndarray) -> jnp.ndarray:
        return csr_spmv(self.csr, x)


@jax.tree_util.register_pytree_node_class
class LaplacianMatrix(SparseMatrix):
    """Implicit graph Laplacian L = D − A (reference laplacian_matrix_t,
    :300); ``diagonal`` is the weighted degree vector."""

    def __init__(self, csr: CSR, diagonal: jnp.ndarray | None = None):
        super().__init__(csr)
        if diagonal is None:
            ones = jnp.ones((csr.n_cols,), dtype=csr.data.dtype)
            diagonal = csr_spmv(csr, ones)
        self.diagonal = diagonal

    def tree_flatten(self):
        return (self.csr, self.diagonal), ()

    def mv(self, x: jnp.ndarray) -> jnp.ndarray:
        return self.diagonal * x - csr_spmv(self.csr, x)


@jax.tree_util.register_pytree_node_class
class ModularityMatrix(LaplacianMatrix):
    """Implicit modularity matrix B = A − d dᵀ / (2E) (reference
    modularity_matrix_t, :372); ``edge_sum`` = ‖d‖₁ = 2E (:382)."""

    def __init__(self, csr: CSR, diagonal: jnp.ndarray | None = None):
        super().__init__(csr, diagonal)
        self.edge_sum = jnp.sum(jnp.abs(self.diagonal))

    def mv(self, x: jnp.ndarray) -> jnp.ndarray:
        d = self.diagonal
        return csr_spmv(self.csr, x) - d * (jnp.dot(d, x) / self.edge_sum)
