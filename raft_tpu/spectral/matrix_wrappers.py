"""Implicit matrix operators for spectral methods.

Reference: spectral/matrix_wrappers.hpp — ``sparse_matrix_t`` with cuSPARSE
``mv()`` (:126,180), ``laplacian_matrix_t`` (D−A as an implicit operator,
:300), ``modularity_matrix_t`` (A − d dᵀ/2E, :372).

TPU design: operators are lightweight pytrees exposing ``mv(x)``; the SpMV
is the gather + segment-sum kernel (sparse/linalg.py), and the Laplacian /
modularity corrections are rank-1 vector updates fused by XLA.  Everything
stays functional so an operator can cross a ``jit`` boundary as a pytree
(the Lanczos driver takes the operator as a traced argument).

Small-graph densification: an nnz-sized element gather is the slow shape
on a TPU (serial scalar loop — the r4 per-row-gather finding applies to
1-D LUT gathers too), while a dense (n, n) matvec is MXU food.  On a TPU
backend, operators therefore auto-densify when the dense matrix fits a
small budget (n_rows·n_cols ≤ 2²² ≈ 16 MB f32, e.g. the 2k-vertex
spectral bench graph); ``densify=`` overrides either way.  On CPU the
gather + segment-sum is the faster shape (measured: 2k steady 0.01 s
sparse vs 0.06 s dense), so auto keeps the sparse path there.  Large
graphs keep the sparse path everywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core import tuning
from raft_tpu.core.utils import is_tpu_backend
from raft_tpu.sparse.formats import CSR
from raft_tpu.sparse.linalg import csr_spmv

# auto-densify budget (elements): 2**22 f32 = 16 MiB
_DENSIFY_ELEMS = 1 << 22


@jax.tree_util.register_pytree_node_class
class SparseMatrix:
    """CSR operator with ``mv`` (reference sparse_matrix_t, :126).

    Pytree protocol: each class lists its array leaves in
    ``_leaf_fields`` (one place to extend per subclass); flatten reads
    them in order, unflatten restores them VERBATIM via ``__new__`` —
    never through ``__init__``, whose densify/derivations must not
    re-run inside a trace.
    """

    _leaf_fields = ("csr", "dense")

    def __init__(self, csr: CSR, densify: bool | None = None,
                 spmv_impl: str | None = None):
        # fail a typo'd pin HERE, at construction — not attempts deep
        # inside the jitted Lanczos solve that consumes the operator
        # (registry legality, shared LogicError message shape)
        if spmv_impl is not None:
            tuning.check("spmv_impl", spmv_impl, site="SparseMatrix",
                         explicit=True)
        self.csr = csr
        if densify is None:
            densify = (is_tpu_backend()
                       and csr.n_rows * csr.n_cols <= _DENSIFY_ELEMS)
        self.dense = csr.to_dense() if densify else None
        # pinned SpMV impl (None = the config knob at trace time).  AUX
        # data, not a leaf: it participates in the treedef, so two
        # operators pinned to different impls compile to different
        # executables — a config-only switch cannot reach an
        # already-compiled solver (the raft_tpu.config caveat; this
        # probe-bit the r5 spectral A/B until the pin existed)
        self.spmv_impl = spmv_impl

    def tree_flatten(self):
        return (tuple(getattr(self, f) for f in self._leaf_fields),
                (self.spmv_impl,))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        obj = cls.__new__(cls)
        for f, v in zip(cls._leaf_fields, leaves):
            setattr(obj, f, v)
        obj.spmv_impl = aux[0]
        return obj

    @property
    def n_rows(self) -> int:
        return self.csr.n_rows

    def _ax(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.dense is not None:
            return jnp.matmul(self.dense, x, precision="highest")
        return csr_spmv(self.csr, x, impl=self.spmv_impl)

    def mv(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._ax(x)


@jax.tree_util.register_pytree_node_class
class LaplacianMatrix(SparseMatrix):
    """Implicit graph Laplacian L = D − A (reference laplacian_matrix_t,
    :300); ``diagonal`` is the weighted degree vector."""

    _leaf_fields = ("csr", "dense", "diagonal")

    def __init__(self, csr: CSR, diagonal: jnp.ndarray | None = None,
                 densify: bool | None = None,
                 spmv_impl: str | None = None):
        super().__init__(csr, densify=densify, spmv_impl=spmv_impl)
        if diagonal is None:
            if self.dense is not None:
                # degree from the dense form (one MXU-friendly row sum)
                # rather than paying the sparse kernel's element gather
                # the densification exists to avoid
                diagonal = jnp.sum(self.dense, axis=1)
            else:
                ones = jnp.ones((csr.n_cols,), dtype=csr.data.dtype)
                # the pin covers EVERY matvec the operator performs,
                # the degree precompute included
                diagonal = csr_spmv(csr, ones, impl=self.spmv_impl)
        self.diagonal = diagonal

    def mv(self, x: jnp.ndarray) -> jnp.ndarray:
        return self.diagonal * x - self._ax(x)


@jax.tree_util.register_pytree_node_class
class ModularityMatrix(LaplacianMatrix):
    """Implicit modularity matrix B = A − d dᵀ / (2E) (reference
    modularity_matrix_t, :372); ``edge_sum`` = ‖d‖₁ = 2E (:382)."""

    _leaf_fields = ("csr", "dense", "diagonal", "edge_sum")

    def __init__(self, csr: CSR, diagonal: jnp.ndarray | None = None,
                 densify: bool | None = None,
                 spmv_impl: str | None = None):
        super().__init__(csr, diagonal, densify=densify,
                         spmv_impl=spmv_impl)
        self.edge_sum = jnp.sum(jnp.abs(self.diagonal))

    def mv(self, x: jnp.ndarray) -> jnp.ndarray:
        d = self.diagonal
        return self._ax(x) - d * (jnp.dot(d, x) / self.edge_sum)
