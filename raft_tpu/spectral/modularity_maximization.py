"""Modularity-based community detection.

Reference: spectral/modularity_maximization.hpp — largest eigenvectors of
the modularity matrix B = A − d dᵀ/2E (:83), whiten, k-means;
``analyzeModularity`` (:143): Q = Σ_c x_cᵀ B x_c / ‖d‖₁.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from raft_tpu.sparse.formats import CSR
from raft_tpu.spectral._driver import solve_embed_cluster
from raft_tpu.spectral.cluster_solvers import KmeansSolver
from raft_tpu.spectral.eigen_solvers import LanczosSolver
from raft_tpu.spectral.matrix_wrappers import ModularityMatrix
from raft_tpu.spectral.spectral_util import construct_indicator


class ModularityResult(NamedTuple):
    clusters: jnp.ndarray
    eig_vals: jnp.ndarray
    eig_vecs: jnp.ndarray
    iters_eig: int
    iters_cluster: jnp.ndarray


def modularity_maximization(csr: CSR,
                            eigen_solver: Optional[LanczosSolver] = None,
                            cluster_solver: Optional[KmeansSolver] = None,
                            n_clusters: int = 2,
                            n_eig_vecs: Optional[int] = None
                            ) -> ModularityResult:
    """(reference modularity_maximization, modularity_maximization.hpp:83)"""
    B = ModularityMatrix(csr)
    return ModularityResult(*solve_embed_cluster(
        B, csr.n_rows, "largest", eigen_solver, cluster_solver,
        n_clusters, n_eig_vecs))


def analyze_modularity(csr: CSR, n_clusters: int, clusters: jnp.ndarray
                       ) -> jnp.ndarray:
    """Modularity Q of a clustering (reference analyzeModularity,
    modularity_maximization.hpp:143)."""
    B = ModularityMatrix(csr)
    q = jnp.asarray(0.0, jnp.float32)
    for c in range(n_clusters):
        _, quad, ok = construct_indicator(c, clusters, B)
        q = q + jnp.where(ok, quad, 0.0)
    return q / B.edge_sum
