"""Spectral graph partitioning.

Reference: spectral/partition.hpp:65-113 — Laplacian → smallest
eigenvectors → whiten → k-means; quality metrics ``analyzePartition``
(:133): edge cut and ratio-cut cost.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from raft_tpu.core.handle import record_on_handle
from raft_tpu.core.profiler import profiled
from raft_tpu.sparse.formats import CSR
from raft_tpu.spectral._driver import solve_embed_cluster
from raft_tpu.spectral.cluster_solvers import KmeansSolver
from raft_tpu.spectral.eigen_solvers import LanczosSolver
from raft_tpu.spectral.matrix_wrappers import LaplacianMatrix
from raft_tpu.spectral.spectral_util import construct_indicator


class PartitionResult(NamedTuple):
    clusters: jnp.ndarray   # (n,) int32 labels
    eig_vals: jnp.ndarray   # (n_eig_vecs,)
    eig_vecs: jnp.ndarray   # (n, n_eig_vecs)
    iters_eig: int
    iters_cluster: jnp.ndarray


@profiled("spectral")
def partition(csr: CSR,
              eigen_solver: Optional[LanczosSolver] = None,
              cluster_solver: Optional[KmeansSolver] = None,
              n_clusters: int = 2,
              n_eig_vecs: Optional[int] = None,
              handle=None) -> PartitionResult:
    """Spectral partition of an (undirected, symmetric) graph (reference
    spectral::partition, partition.hpp:65; takes ``handle_t&`` first).

    Default solvers mirror the reference configs when not supplied.
    ``handle``: optional resource context; the result arrays are recorded
    on its main stream so ``sync_stream``/``stream_syncer`` cover them.
    """
    L = LaplacianMatrix(csr)
    res = PartitionResult(*solve_embed_cluster(
        L, csr.n_rows, "smallest", eigen_solver, cluster_solver,
        n_clusters, n_eig_vecs))
    record_on_handle(handle, res.clusters, res.eig_vals, res.eig_vecs)
    return res


@profiled("spectral")
def analyze_partition(csr: CSR, n_clusters: int, clusters: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(edge_cut, cost) quality metrics (reference analyzePartition,
    partition.hpp:133): per cluster, the Laplacian quadratic form of the
    indicator gives its cut; cost is the ratio-cut Σ cut_c / size_c."""
    L = LaplacianMatrix(csr)
    edge_cut = jnp.asarray(0.0, jnp.float32)
    cost = jnp.asarray(0.0, jnp.float32)
    for c in range(n_clusters):
        size, quad, ok = construct_indicator(c, clusters, L)
        # quad = x_cᵀ L x_c (0/1 indicator) = cut(c, rest)
        cost = cost + jnp.where(ok, quad / jnp.maximum(size, 1.0), 0.0)
        edge_cut = edge_cut + jnp.where(ok, quad, 0.0) / 2.0
    return edge_cut, cost
