"""Shared eigenvector-embedding → clustering driver.

Both spectral entry points (partition.hpp:65, modularity_maximization.hpp:83)
are the same pipeline modulo (operator class, which end of the spectrum):
solve eigenvectors, whiten, k-means.  This helper holds that pipeline once.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from raft_tpu.spectral.cluster_solvers import ClusterSolverConfig, KmeansSolver
from raft_tpu.spectral.eigen_solvers import EigenSolverConfig, LanczosSolver
from raft_tpu.spectral.spectral_util import transform_eigen_matrix


def solve_embed_cluster(op, n: int, which: str,
                        eigen_solver: Optional[LanczosSolver],
                        cluster_solver: Optional[KmeansSolver],
                        n_clusters: int,
                        n_eig_vecs: Optional[int]
                        ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                   int, jnp.ndarray]:
    """Returns (labels, eig_vals, eig_vecs, iters_eig, iters_cluster)."""
    if n_eig_vecs is None:
        n_eig_vecs = (eigen_solver.config.n_eig_vecs
                      if eigen_solver is not None else n_clusters)
    if eigen_solver is None:
        eigen_solver = LanczosSolver(EigenSolverConfig(n_eig_vecs=n_eig_vecs))
    if cluster_solver is None:
        cluster_solver = KmeansSolver(
            ClusterSolverConfig(n_clusters=n_clusters))

    solve = (eigen_solver.solve_smallest_eigenvectors if which == "smallest"
             else eigen_solver.solve_largest_eigenvectors)
    vals, vecs, it_eig = solve(op, n)
    emb = transform_eigen_matrix(vecs)
    labels, _, it_clu = cluster_solver.solve(emb)
    return labels, vals, vecs, it_eig, it_clu
