"""raft_tpu: a TPU-native reusable ML/analytics primitives framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of RAFT
(RAPIDS Analytics Framework Toolkit): dense/sparse linear algebra,
pairwise distances, k-NN, clustering (spectral / hierarchical), solvers,
RNG, and a multi-device communicator abstraction — built TPU-first:

- MXU-shaped compute: distances and contractions lower to large batched
  matmuls or Pallas kernels, bfloat16/float32 on the systolic array.
- SPMD over device meshes: ``jax.sharding.Mesh`` + ``shard_map`` with XLA
  collectives replaces the reference's NCCL/UCX/MPI communicator
  (reference: cpp/include/raft/comms/).
- Functional, jit-compatible APIs: primitives are pure functions over JAX
  arrays; the ``Handle`` carries device/mesh/comms resources the way the
  reference's ``raft::handle_t`` carries streams and vendor-library handles
  (reference: cpp/include/raft/handle.hpp:49).

Layout (mirrors the reference's module inventory, see SURVEY.md section 2):

- ``raft_tpu.core``     — handle, errors, tracing, integer/pow2 utilities
- ``raft_tpu.linalg``   — gemm/gemv/eig/svd/qr, reductions, norms, lanczos
- ``raft_tpu.matrix``   — matrix manipulation + math helpers
- ``raft_tpu.stats``    — mean/stddev/sum/mean_center
- ``raft_tpu.random``   — Rng with the reference's distribution set
- ``raft_tpu.distance`` — pairwise distances (15+ metrics), fused_l2_nn
- ``raft_tpu.spatial``  — brute-force / fused kNN, select_k, ball cover, ANN
- ``raft_tpu.sparse``   — COO/CSR, conversions, ops, distances, kNN, MST,
                          single-linkage hierarchy
- ``raft_tpu.spectral`` — Laplacian/modularity operators, eigen + cluster
                          solvers, partition, modularity maximization
- ``raft_tpu.label``    — label relabeling / merging utilities
- ``raft_tpu.cache``    — set-associative vector cache
- ``raft_tpu.lap``      — linear assignment problem solver
- ``raft_tpu.comms``    — comms_t-shaped collective/p2p interface over XLA
                          collectives (ICI/DCN), mesh sub-communicators
- ``raft_tpu.serve``    — dynamic micro-batching query engine: shape
                          buckets + warmup, admission control, deadlines,
                          graceful drain (docs/SERVING.md)
"""

__version__ = "0.1.0"

from raft_tpu import config  # noqa: F401
from raft_tpu.core.error import (  # noqa: F401
    AllocationError,
    CommAbortedError,
    CommError,
    CommTimeoutError,
    RaftError,
    ServiceOverloadError,
    expects,
    fail,
)
from raft_tpu.core.handle import Handle  # noqa: F401
